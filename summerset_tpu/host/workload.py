"""Deterministic workload plane: seeded adversarial traffic schedules.

The workload twin of ``host/nemesis.py``: where a ``FaultPlan`` decides
*what breaks and when*, a ``WorkloadPlan`` decides *what traffic arrives
and when* — and both obey the same determinism contract, enforced by the
same lint (graftlint H103 covers this module's plan/stream classes):
``WorkloadPlan.generate(seed, wl_class, ...)`` draws only from
``random.Random`` seeded off its arguments, so the same seed always
yields a byte-identical ``timeline()`` and the same per-client op
sequence.  Every overload bug found under a workload schedule is a
one-line repro (``--wl-class C --seed N``), and the joint
workload × nemesis soak (``scripts/workload_soak.py``) replays BOTH
schedules from their seeds.

Classes are YCSB-style (PAPERS.md: compartmentalized SMR and HT-Paxos
both assume an ingress tier that batches and absorbs client load; these
classes are the traffic that tier must absorb):

- ``uniform``      — uniform keys, balanced mix (the legacy bench class);
- ``read_mostly``  — zipfian hot keys, ~5-10% puts (YCSB-B territory);
- ``write_heavy``  — zipfian hot keys, ~85-95% puts (ingest pressure on
                     the log + WAL planes);
- ``value_mix``    — log-uniform value sizes over a wide range (frame
                     encoder / payload-plane stress);
- ``multi_tenant`` — per-client private key ranges plus a small shared
                     hot range (the KeyRangeMap routing scenario);
- ``hot_burst``    — strong zipfian skew plus an open-loop arrival
                     schedule whose burst phase offers ~2x the ingress
                     capacity: the overload-survival scenario (bounded
                     queues must shed visibly, not buffer unboundedly);
- ``ycsb_e``       — YCSB-E: ~95% ordered range scans (zipfian scan
                     start, uniform scan length) + ~5% puts — the
                     learner-read-tier scan showcase;
- ``trace``        — replay of an external YCSB trace file normalized
                     by :meth:`WorkloadPlan.from_trace`: the ops ARE
                     the trace rows (strided per client), and the
                     timeline embeds the trace digest so external
                     traces become byte-reproducible soak cells.

Split of responsibilities: everything *logical* (op kinds, keys, value
sizes, phase structure, rate multipliers) lives here and is a pure
function of the seed; everything *temporal* (mapping phase ticks to wall
seconds, expovariate arrival pacing against the monotonic clock) lives
in the drivers (``client/drivers.DriverOpenLoopPaced`` and the soak
runner), exactly as ``NemesisRunner`` owns wall pacing for fault plans.
"""

from __future__ import annotations

import bisect
import dataclasses
import hashlib
import zlib
from typing import List, Tuple

#: every workload class the plane knows how to generate
WORKLOAD_CLASSES = (
    "uniform",
    "read_mostly",
    "write_heavy",
    "value_mix",
    "multi_tenant",
    "hot_burst",
    "ycsb_e",
    "trace",
)


@dataclasses.dataclass(frozen=True)
class WorkloadPhase:
    """One open-loop arrival phase.  ``tick``/``ticks`` are workload
    schedule ticks (the runner maps them to wall seconds with its
    ``tick_len``, sharing the logical clock with the FaultPlan playing
    alongside); ``rate_x`` is the offered-arrival multiplier relative to
    the serving path's ingress capacity (``api_max_batch / tick``) — a
    phase with ``rate_x >= 1`` offers more than the ingress tier can
    drain and MUST surface as visible shedding, not unbounded queues."""

    tick: int
    ticks: int
    rate_x: float

    def render(self) -> str:
        return (
            f"@{self.tick:05d} phase rate_x={self.rate_x:g}"
            f" ticks={self.ticks}"
        )


@dataclasses.dataclass(frozen=True)
class WorkloadPlan:
    seed: int
    wl_class: str
    clients: int
    num_keys: int
    put_ratio: float
    zipf_s: float           # 0 = uniform key popularity
    value_lo: int
    value_hi: int
    log_values: bool        # log-uniform (vs uniform) value sizes
    tenant_span: int        # >0: per-client private key range width
    shared_keys: int        # multi-tenant: size of the shared hot range
    shared_frac: float      # fraction of multi-tenant ops on shared keys
    phases: Tuple[WorkloadPhase, ...]
    # ordered-range-read knobs (ycsb_e; default-zero keeps every older
    # plan's constructor call AND timeline byte-identical)
    scan_frac: float = 0.0  # fraction of non-put ops issued as scans
    scan_max: int = 0       # uniform scan length in [1, scan_max]
    # normalized external trace rows (wl_class "trace"): the op sequence
    # IS this tuple, strided per client by OpStream
    trace: Tuple[Tuple[str, str, int], ...] = ()

    # ------------------------------------------------------------ build
    @staticmethod
    def generate(
        seed: int,
        wl_class: str,
        clients: int = 3,
        num_keys: int = 24,
        horizon: int = 120,
    ) -> "WorkloadPlan":
        """Draw a plan from the seed.  Class parameters are jittered
        per-seed inside each class's envelope, so different seeds of the
        same class are genuinely different workloads while the class's
        character (skew, mix, burst shape) is preserved."""
        import random

        if wl_class not in WORKLOAD_CLASSES:
            raise ValueError(f"unknown workload class {wl_class!r}")
        # class-salted seed: seed 1 of read_mostly and seed 1 of
        # write_heavy must not share a random stream
        rng = random.Random(
            (seed << 16) ^ zlib.crc32(wl_class.encode())
        )
        put_ratio, zipf_s = 0.5, 0.0
        value_lo, value_hi, log_values = 48, 64, False
        tenant_span, shared_keys, shared_frac = 0, 0, 0.0
        scan_frac, scan_max = 0.0, 0
        steady = round(0.25 + rng.uniform(0.0, 0.15), 3)
        phases: List[WorkloadPhase] = [
            WorkloadPhase(0, horizon, steady)
        ]
        if wl_class == "read_mostly":
            put_ratio = round(rng.uniform(0.04, 0.10), 3)
            zipf_s = round(rng.uniform(0.9, 1.2), 3)
            value_lo, value_hi = 32, 128
        elif wl_class == "write_heavy":
            put_ratio = round(rng.uniform(0.85, 0.95), 3)
            zipf_s = round(rng.uniform(0.8, 1.1), 3)
            value_lo, value_hi = 64, 192
        elif wl_class == "value_mix":
            value_lo, value_hi, log_values = 16, 2048, True
        elif wl_class == "multi_tenant":
            put_ratio = round(rng.uniform(0.3, 0.5), 3)
            tenant_span = rng.randint(6, 10)
            shared_keys = rng.randint(3, 5)
            shared_frac = round(rng.uniform(0.2, 0.4), 3)
            num_keys = clients * tenant_span + shared_keys
        elif wl_class == "hot_burst":
            zipf_s = round(rng.uniform(1.1, 1.3), 3)
            # steady → burst (~2x ingress capacity) → recover; the
            # recover tail is where the soak measures throughput
            # returning to the pre-burst steady state
            t1 = int(horizon * rng.uniform(0.28, 0.34))
            blen = int(horizon * rng.uniform(0.22, 0.28))
            burst_x = round(rng.uniform(1.9, 2.2), 3)
            phases = [
                WorkloadPhase(0, t1, steady),
                WorkloadPhase(t1, blen, burst_x),
                WorkloadPhase(t1 + blen, horizon - t1 - blen, steady),
            ]
        elif wl_class == "ycsb_e":
            # YCSB workload E: short ordered scans dominate, a thin
            # insert/update stream keeps the scanned state moving.
            # Scan start is zipfian (the shared hot-key shuffle below),
            # scan LENGTH is uniform in [1, scan_max] — the canonical
            # E shape (zipfian request keys, uniform scan lengths)
            put_ratio = round(rng.uniform(0.03, 0.07), 3)
            zipf_s = round(rng.uniform(0.9, 1.2), 3)
            value_lo, value_hi = 32, 96
            scan_frac = round(rng.uniform(0.9, 1.0), 3)
            scan_max = rng.randint(6, 12)
        elif wl_class == "trace":
            raise ValueError(
                "wl_class 'trace' plans come from WorkloadPlan."
                "from_trace, not generate()"
            )
        return WorkloadPlan(
            seed, wl_class, clients, num_keys, put_ratio, zipf_s,
            value_lo, value_hi, log_values, tenant_span, shared_keys,
            shared_frac, tuple(phases), scan_frac, scan_max,
        )

    @staticmethod
    def from_trace(
        rows,
        seed: int = 0,
        clients: int = 3,
        horizon: int = 120,
        rate_x: float = 0.3,
    ) -> "WorkloadPlan":
        """Normalize real YCSB trace rows into the seeded plan contract.

        ``rows`` is a path to a trace file or an iterable of its lines;
        accepted row shapes are the YCSB runner's operation lines —
        ``READ <table> <key> ...``, ``INSERT|UPDATE <table> <key>
        <fields...>``, ``SCAN <table> <startkey> <len> ...`` — plus the
        bare 2/3-column form (``op key [len]``).  Unknown lines are
        skipped, not errors (real trace dumps interleave progress
        noise).  Parsing is PURE (H103: no wallclock, no unseeded
        randomness, no pacing — the drivers own time): the same bytes
        always yield the same plan, and :meth:`timeline` embeds the
        normalized rows' sha256, so same trace ⇒ same digest is a
        checkable contract, not a convention.  ``seed`` only salts the
        client-stride offset, keeping distinct cells distinguishable
        without touching the rows."""
        if isinstance(rows, (str, bytes)):
            with open(rows, "r", encoding="utf-8",
                      errors="replace") as f:
                lines = f.read().splitlines()
        else:
            lines = [str(r) for r in rows]
        ops: List[Tuple[str, str, int]] = []
        for line in lines:
            parts = line.split()
            if len(parts) < 2:
                continue
            verb = parts[0].upper()
            # full YCSB rows carry the table name second; the bare form
            # puts the key there — disambiguate by verb arity
            rest = parts[1:]
            if verb in ("READ", "INSERT", "UPDATE", "SCAN") \
                    and len(rest) >= 2 and not rest[1].isdigit():
                rest = rest[1:]  # drop the table column
            key = rest[0]
            if verb == "READ":
                ops.append(("get", key, 0))
            elif verb in ("INSERT", "UPDATE"):
                # field payload sizes vary per dump; normalize to the
                # joined field text length (bounded below so empty
                # fields still write a real value)
                size = max(8, len(" ".join(rest[1:])))
                ops.append(("put", key, min(size, 2048)))
            elif verb == "SCAN":
                n = 1
                if len(rest) >= 2:
                    try:
                        n = max(1, int(rest[1]))
                    except ValueError:
                        n = 1
                ops.append(("scan", key, min(n, 64)))
        if not ops:
            raise ValueError("trace contains no recognizable ops")
        keys = {k for _, k, _ in ops}
        puts = sum(1 for o in ops if o[0] == "put")
        return WorkloadPlan(
            seed, "trace", clients, len(keys),
            round(puts / len(ops), 3), 0.0, 8, 2048, False, 0, 0, 0.0,
            (WorkloadPhase(0, horizon, rate_x),),
            0.0, 0, tuple(ops),
        )

    # ------------------------------------------------------- determinism
    def timeline(self) -> str:
        """Canonical rendering; byte-identical for identical plans (the
        repro contract — soak failures print this plus the seed)."""
        head = (
            f"# WorkloadPlan v1 seed={self.seed} class={self.wl_class}"
            f" clients={self.clients}\n"
            f"keys={self.num_keys} put={self.put_ratio:g}"
            f" zipf={self.zipf_s:g}"
            f" value=[{self.value_lo},{self.value_hi}"
            f"{',log' if self.log_values else ''}]"
            f" tenant_span={self.tenant_span}"
            f" shared={self.shared_keys}@{self.shared_frac:g}\n"
        )
        # scan/trace lines render ONLY when the knobs are live, so every
        # pre-scan plan's timeline (and committed digest) is unchanged
        if self.scan_frac > 0.0 or self.scan_max > 0:
            head += (
                f"scan={self.scan_frac:g}@max{self.scan_max}\n"
            )
        if self.trace:
            head += (
                f"trace_sha={self.trace_sha()} rows={len(self.trace)}\n"
            )
        return head + "".join(p.render() + "\n" for p in self.phases)

    def trace_sha(self) -> str:
        """sha256 over the normalized trace rows (canonical rendering):
        the byte-reproducibility anchor — same trace file, same
        normalization, same sha, same plan digest."""
        h = hashlib.sha256()
        for kind, key, n in self.trace:
            h.update(f"{kind} {key} {n}\n".encode())
        return h.hexdigest()[:16]

    def digest(self) -> str:
        return hashlib.sha256(self.timeline().encode()).hexdigest()[:16]

    # ---------------------------------------------------------- streams
    def rate_x_at(self, tick: float) -> float:
        """Offered-rate multiplier at a workload tick (0 past the
        horizon — issuing stops, inflight ops drain)."""
        for p in self.phases:
            if p.tick <= tick < p.tick + p.ticks:
                return p.rate_x
        return 0.0

    def horizon(self) -> int:
        return max(p.tick + p.ticks for p in self.phases)

    def opstream(self, ci: int) -> "OpStream":
        """The per-client op stream: a pure function of (plan, ci)."""
        return OpStream(self, ci)


class OpStream:
    """Seeded per-client op generator: ``next()`` yields
    ``(kind, key, value_size)`` tuples drawn from this client's own
    ``random.Random`` — replaying a client from the same (plan, ci)
    yields the identical op sequence.

    Key popularity: zipfian over a per-plan shuffled key order (the hot
    key identity varies per seed but is SHARED across clients, so skew
    creates real cross-client contention).  Multi-tenant plans route
    ``shared_frac`` of ops to the shared hot range and the rest to this
    client's private range (disjoint from every other client's)."""

    def __init__(self, plan: WorkloadPlan, ci: int):
        import random

        self.plan = plan
        self.ci = int(ci)
        self._rng = random.Random(
            plan.seed * 7919 + self.ci * 104729 + 13
        )
        if plan.trace:
            # trace replay: this client's rows are the seed-rotated
            # per-client stride of the normalized trace — every row is
            # issued by exactly one client, and the union across
            # clients is the trace itself
            off = (self.ci + plan.seed) % max(plan.clients, 1)
            self._trows = plan.trace[off::max(plan.clients, 1)] \
                or plan.trace
            self._tpos = 0
            self.keys = []
            self._shared, self._private = [], []
            self._cdf = []
            return
        if plan.tenant_span > 0:
            self._shared = [
                f"t_shared{i}" for i in range(plan.shared_keys)
            ]
            self._private = [
                f"t{self.ci}_k{j}" for j in range(plan.tenant_span)
            ]
            self.keys = self._shared + self._private
            self._cdf: List[float] = []
        else:
            # per-plan (client-shared) hot-key identity: one shuffle
            # seeded off the plan alone
            order = list(range(plan.num_keys))
            random.Random((plan.seed << 8) | 0xA5).shuffle(order)
            self.keys = [f"w{i}" for i in order]
            self._shared, self._private = [], []
            s = plan.zipf_s
            if s > 0:
                w = [1.0 / ((i + 1) ** s) for i in range(plan.num_keys)]
                tot = sum(w)
                acc, cdf = 0.0, []
                for x in w:
                    acc += x / tot
                    cdf.append(acc)
                self._cdf = cdf
            else:
                self._cdf = []

    def _pick_key(self) -> str:
        p = self.plan
        if p.tenant_span > 0:
            if self._shared and self._rng.random() < p.shared_frac:
                return self._rng.choice(self._shared)
            return self._rng.choice(self._private)
        if self._cdf:
            i = bisect.bisect_left(self._cdf, self._rng.random())
            return self.keys[min(i, len(self.keys) - 1)]
        return self._rng.choice(self.keys)

    def _pick_size(self) -> int:
        p = self.plan
        if p.value_hi <= p.value_lo:
            return p.value_lo
        if p.log_values:
            # log-uniform: small values dominate, the tail reaches
            # value_hi (frame-encoder stress without every op paying it)
            import math

            lo, hi = math.log(p.value_lo), math.log(p.value_hi)
            return int(round(math.exp(self._rng.uniform(lo, hi))))
        return self._rng.randint(p.value_lo, p.value_hi)

    def next(self) -> Tuple[str, str, int]:
        """One op: ``(kind, key, arg)`` — ``("put", key, value_size)``,
        ``("get", key, 0)``, or ``("scan", start_key, scan_len)``."""
        if self.plan.trace:
            op = self._trows[self._tpos % len(self._trows)]
            self._tpos += 1
            return op
        key = self._pick_key()
        if self._rng.random() < self.plan.put_ratio:
            return "put", key, self._pick_size()
        if self.plan.scan_max > 0 \
                and self._rng.random() < self.plan.scan_frac:
            return "scan", key, self._rng.randint(
                1, self.plan.scan_max
            )
        return "get", key, 0
