"""Host-side runtime around the device engine.

Parity: the reference's server runtime modules (``src/server/``,
SURVEY.md §2.2) — StateMachine, StorageHub, ExternalApi, ControlHub,
TransportHub — re-homed as the host half of the TPU-native design: the
device runs the vectorized consensus control plane; these modules own
client I/O, durability, the KV store, and the control plane.
"""

from .payload import PayloadStore  # noqa: F401
from .statemach import Command, CommandResult, StateMachine  # noqa: F401
from .storage import LogAction, LogResult, StorageHub  # noqa: F401
