"""graftscope flight recorder: a fixed-size ring of typed, monotonic-
stamped events per server — the causal-trace complement of the
aggregate telemetry plane (``host/telemetry.py``).

Where the metrics registry answers "how slow, on average, is each
seam", the flight recorder answers "what exactly was this replica doing
in its final ticks" and "where did THIS request spend its time": every
hub seam logs a compact event into one per-server ring buffer —

- ``api_ingress`` / ``api_reply`` — client plane (client, req_id);
- ``propose``                    — a sampled batch entered the log
                                   ((g, vid) plus the representative
                                   (client, req_id) that connects the
                                   request span to the slot span);
- ``tick``                       — one run-loop iteration with its
                                   stage durations (the loop_stage_us
                                   stopwatches as child spans; the
                                   ``step`` stage is the device scan);
- ``frame_tx`` / ``frame_rx``    — transport frames with (peer, seq):
                                   ``seq`` is the sender's tick number,
                                   which already rides the wire, so tx
                                   and rx pair at export time across two
                                   servers' dumps with no wire change;
- ``wal_append`` / ``wal_fsync`` — storage plane (fsync carries the
                                   group-commit batch size + duration);
- ``commit`` / ``apply``         — a slot passed the commit bar / was
                                   applied, on every replica (not just
                                   the proposer);
- ``fault_ctl`` / ``crash`` / ``restart`` — nemesis actions, supervisor-
                                   observed crashes, and recovery.

The ring is lock-cheap: one mutex guarding a bounded ``deque`` append
(the write path is an int stamp + tuple append, ~1us); overflow drops
the OLDEST events and the drop count is part of every dump, so a
truncated view is always visible as truncated.  Stamps are
``time.monotonic()`` microseconds — never wallclock, which can jump and
reorder spans (graftlint H103 enforces this for the whole module).

Dumps travel the ctrl plane: ``CtrlRequest("flight_dump")`` fans out and
gathers ``{sid: dump}`` exactly like ``metrics_dump``; NemesisRunner
failure repro bundles and the test_cluster supervisor's crash reports
attach the last-N tails automatically.  ``scripts/trace_export.py``
merges per-server dumps into one Chrome-trace/Perfetto timeline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: the event taxonomy (dump consumers index by these names; appending is
#: fine, renames invalidate committed TRACE.json artifacts)
EVENT_TYPES = (
    "api_ingress",   # client request hit the api plane (client, req_id)
    "api_reply",     # reply left the api plane (client, req_id, kind)
    "api_shed",      # ingress backpressure refused a request before it
                     # entered the queue (client, req_id, retry_ms,
                     # depth) — overload is attributable on graftscope
                     # request chains instead of vanishing silently
    "propose",       # sampled batch proposed (g, vid, tick, client, req_id)
    "tick",          # run-loop iteration (tick, per-stage durations us;
                     # pipelined ticks additionally carry pipelined=1
                     # plus the overlap/device_wait attribution stages —
                     # host-work us coincident with the in-flight device
                     # step, and us spent blocked on its results)
    "device_step",   # pipelined mode: one DEVICE step's true wall span
                     # (tick, dur_us = dispatch -> results ready,
                     # wait_us = the host's residual blocked share).
                     # Recorded at drain time, on its own track, so the
                     # exporter renders the scan as a genuinely
                     # overlapping span beside the host "overlap" stage
                     # instead of nesting it inside the host tick span
    "frame_tx",      # p2p frame sent (peer=dst, seq=sender tick, nbytes)
    "frame_rx",      # p2p frame received (peer=src, seq=sender tick, nbytes)
    "wal_append",    # WAL record appended (sync flag)
    "wal_fsync",     # group-commit durability point (dur_us, batch)
    "commit",        # slot passed the commit bar (g, vid, slot, tick)
    "apply",         # slot applied to the KV (g, vid, slot, tick)
    "proxy_fwd",     # ingress proxy forwarded an op to an owner shard
                     # (sid, prid, client, req_id, fwd_id) — pairs with
                     # the shard's api_ingress where client == fwd_id
                     # and req_id == prid, giving trace_export the
                     # client→proxy→shard flow arrow with no wire change
    "proxy_rcv",     # upstream reply returned to the proxy (sid, prid,
                     # kind) — the shard→proxy half of the hop chain
    "read_serve",    # read tier served a get from learner state
                     # (client, req_id, seq) — the probe-gated
                     # lease-local read that never touched the proposer
    "scan_serve",    # ordered range read served (keys, tick on the
                     # fused path; client, req_id, seq on the learner
                     # tier) — one event per scan wherever it was cut
    "fault_ctl",     # nemesis fault_ctl received (planes touched)
    "demote",        # health plane indicted THIS replica's leadership and
                     # the server voluntarily stepped down (signals, the
                     # quorum-median table row, mitigation path) — the
                     # demotion instant on the exported ctrl track
    "crash",         # supervisor-observed crash (error)
    "restart",       # bring-up recovery completed (wal records, applied
                     # floor; cold=True means first boot, empty backer)
    "range_seal",    # live resharding: a key range sealed for cutover
                     # (rc_id, op, tick) — ops on it shed until adopted
    "range_adopt",   # live resharding: the destination group applied
                     # the adopt (rc_id, op, dst, keys, tick) — the
                     # cutover instant on the exported ctrl track
    "range_unseal",  # seal-TTL escape hatch: the source un-sealed a
                     # range whose destination never adopted (rc_id,
                     # why, tick) and resumed serving it
    "autopilot_act", # autopilot actuation applied on this server
                     # (act, plus actuator-specific fields like reason/
                     # api_max_batch/pipeline, tick) — the policy
                     # tier's instant on the exported ctrl track
    "transport_handshake_fail",
                     # a p2p dialer never completed the id handshake
                     # (error) — one stray is a port scan; a stream of
                     # them is codec skew after a partial upgrade, and
                     # without the record the mesh silently never forms
)
_EVENT_SET = frozenset(EVENT_TYPES)

SCHEMA_VERSION = 1


class FlightRecorder:
    """Fixed-size, lock-cheap ring of typed monotonic-stamped events.

    ``enabled=False`` turns every ``record`` into one attribute read —
    the recorder-off variant the tier-2f overhead gate compares against.
    ``capacity`` bounds memory AND dump size; overflow drops oldest.
    """

    def __init__(self, capacity: int = 8192, enabled: bool = True,
                 me: int = -1):
        self.capacity = max(16, int(capacity))
        self.enabled = bool(enabled)
        self.me = me
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=self.capacity)
        self._seq = 0  # events ever recorded (>= len(_buf))
        # incarnation floor: a crash-restarted server gets a FRESH
        # recorder (and restarts its tick counter, reusing wire seqs),
        # so the exporter uses this birth stamp to refuse pairing the
        # new incarnation's frames against a peer's stale rx events
        self._t_start_us = int(time.monotonic() * 1e6)

    # -- write side (every hub seam; hot-path safe) -------------------------
    def record(self, etype: str, **fields: Any) -> None:
        """Append one event.  ``etype`` must be a declared
        :data:`EVENT_TYPES` name — an undeclared type is a contributor
        bug and fails loudly, same policy as the device metric lanes."""
        if not self.enabled:
            return
        if etype not in _EVENT_SET:
            raise KeyError(etype)
        with self._lock:
            # stamp INSIDE the lock: a pre-lock stamp lets a preempted
            # writer append behind a later-stamped peer, breaking the
            # ring's oldest-first stamp order that dumps/tails rely on
            t_us = int(time.monotonic() * 1e6)
            self._buf.append((self._seq, t_us, etype, fields))
            self._seq += 1

    # -- read side -----------------------------------------------------------
    def dump(self, last_n: Optional[int] = None) -> Dict[str, Any]:
        """JSON-able snapshot: the retained events (oldest first, trimmed
        to ``last_n`` newest when given) plus the drop accounting that
        makes truncation visible."""
        with self._lock:
            events = list(self._buf)
            total = self._seq
        if last_n is not None:
            n = int(last_n)
            # n <= 0 means "metadata only" (events[-0:] would be ALL)
            events = events[-n:] if n > 0 else []
        return {
            "v": SCHEMA_VERSION,
            "me": self.me,
            "t_start_us": self._t_start_us,
            "count": total,
            "dropped": total - len(events),
            "t_dump_us": int(time.monotonic() * 1e6),
            # "n" is the ring's own event counter ("seq" stays free for
            # the frame events' wire sequence field)
            "events": [
                {"n": seq, "t_us": t_us, "type": etype, **fields}
                for seq, t_us, etype, fields in events
            ],
        }

    def tail(self, n: int = 64) -> List[str]:
        """The last ``n`` events rendered one per line — the
        crash-report attachment format (test_cluster supervisor)."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            events = list(self._buf)[-n:]
        return [
            f"#{seq} t={t_us}us {etype} " + " ".join(
                f"{k}={fields[k]}" for k in sorted(fields)
            )
            for seq, t_us, etype, fields in events
        ]
