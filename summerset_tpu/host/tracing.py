"""graftscope flight recorder: a fixed-size ring of typed, monotonic-
stamped events per server — the causal-trace complement of the
aggregate telemetry plane (``host/telemetry.py``).

Where the metrics registry answers "how slow, on average, is each
seam", the flight recorder answers "what exactly was this replica doing
in its final ticks" and "where did THIS request spend its time": every
hub seam logs a compact event into one per-server ring buffer —

- ``api_ingress`` / ``api_reply`` — client plane (client, req_id);
- ``propose``                    — a sampled batch entered the log
                                   ((g, vid) plus the representative
                                   (client, req_id) that connects the
                                   request span to the slot span);
- ``tick``                       — one run-loop iteration with its
                                   stage durations (the loop_stage_us
                                   stopwatches as child spans; the
                                   ``step`` stage is the device scan);
- ``frame_tx`` / ``frame_rx``    — transport frames with (peer, seq):
                                   ``seq`` is the sender's tick number,
                                   which already rides the wire, so tx
                                   and rx pair at export time across two
                                   servers' dumps with no wire change;
- ``wal_append`` / ``wal_fsync`` — storage plane (fsync carries the
                                   group-commit batch size + duration);
- ``commit`` / ``apply``         — a slot passed the commit bar / was
                                   applied, on every replica (not just
                                   the proposer);
- ``fault_ctl`` / ``crash`` / ``restart`` — nemesis actions, supervisor-
                                   observed crashes, and recovery.

The ring is lock-cheap: one mutex guarding a bounded ``deque`` append
(the write path is an int stamp + tuple append, ~1us); overflow drops
the OLDEST events and the drop count is part of every dump, so a
truncated view is always visible as truncated.  Stamps are
``time.monotonic()`` microseconds — never wallclock, which can jump and
reorder spans (graftlint H103 enforces this for the whole module).

Drop accounting is PER TYPE (schema v2): a leader's ring is dominated
by high-rate types (frame_tx/rx, tick), which used to silently evict
every rare-but-load-bearing event (demote, range_seal, crash) — TRACE.json
showed sid 0 dropping 27k events while its peers dropped none, with no
way to tell WHAT was lost.  Now every type keeps a small reserve ring
beside the main one (union-deduped at dump time), so a burst of frames
can no longer wash out the last demotion, and every dump carries
``recorded_by_type`` + ``dropped_by_type`` with the invariant
``sum(dropped_by_type.values()) == dropped``.  ``publish_drops``
mirrors the per-type drop counts into ``trace_dropped_total{type=...}``
registry counters at scrape time, and ``scripts/trace_export.py``
fails its schema check when a v2 dump's drops are unaccounted.

Dumps travel the ctrl plane: ``CtrlRequest("flight_dump")`` fans out and
gathers ``{sid: dump}`` exactly like ``metrics_dump``; NemesisRunner
failure repro bundles and the test_cluster supervisor's crash reports
attach the last-N tails automatically.  ``scripts/trace_export.py``
merges per-server dumps into one Chrome-trace/Perfetto timeline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

#: the event taxonomy (dump consumers index by these names; appending is
#: fine, renames invalidate committed TRACE.json artifacts)
EVENT_TYPES = (
    "api_ingress",   # client request hit the api plane (client, req_id)
    "api_reply",     # reply left the api plane (client, req_id, kind)
    "api_shed",      # ingress backpressure refused a request before it
                     # entered the queue (client, req_id, retry_ms,
                     # depth) — overload is attributable on graftscope
                     # request chains instead of vanishing silently
    "propose",       # sampled batch proposed (g, vid, tick, client, req_id)
    "tick",          # run-loop iteration (tick, per-stage durations us;
                     # pipelined ticks additionally carry pipelined=1
                     # plus the overlap/device_wait attribution stages —
                     # host-work us coincident with the in-flight device
                     # step, and us spent blocked on its results)
    "device_step",   # pipelined mode: one DEVICE step's true wall span
                     # (tick, dur_us = dispatch -> results ready,
                     # wait_us = the host's residual blocked share).
                     # Recorded at drain time, on its own track, so the
                     # exporter renders the scan as a genuinely
                     # overlapping span beside the host "overlap" stage
                     # instead of nesting it inside the host tick span
    "frame_tx",      # p2p frame sent (peer=dst, seq=sender tick, nbytes)
    "frame_rx",      # p2p frame received (peer=src, seq=sender tick, nbytes)
    "wal_append",    # WAL record appended (sync flag)
    "wal_fsync",     # group-commit durability point (dur_us, batch)
    "commit",        # slot passed the commit bar (g, vid, slot, tick)
    "apply",         # slot applied to the KV (g, vid, slot, tick)
    "proxy_fwd",     # ingress proxy forwarded an op to an owner shard
                     # (sid, prid, client, req_id, fwd_id) — pairs with
                     # the shard's api_ingress where client == fwd_id
                     # and req_id == prid, giving trace_export the
                     # client→proxy→shard flow arrow with no wire change
    "proxy_rcv",     # upstream reply returned to the proxy (sid, prid,
                     # kind) — the shard→proxy half of the hop chain
    "read_serve",    # read tier served a get from learner state
                     # (client, req_id, seq) — the probe-gated
                     # lease-local read that never touched the proposer
    "scan_serve",    # ordered range read served (keys, tick on the
                     # fused path; client, req_id, seq on the learner
                     # tier) — one event per scan wherever it was cut
    "fault_ctl",     # nemesis fault_ctl received (planes touched)
    "demote",        # health plane indicted THIS replica's leadership and
                     # the server voluntarily stepped down (signals, the
                     # quorum-median table row, mitigation path) — the
                     # demotion instant on the exported ctrl track
    "crash",         # supervisor-observed crash (error)
    "restart",       # bring-up recovery completed (wal records, applied
                     # floor; cold=True means first boot, empty backer)
    "range_seal",    # live resharding: a key range sealed for cutover
                     # (rc_id, op, tick) — ops on it shed until adopted
    "range_adopt",   # live resharding: the destination group applied
                     # the adopt (rc_id, op, dst, keys, tick) — the
                     # cutover instant on the exported ctrl track
    "range_unseal",  # seal-TTL escape hatch: the source un-sealed a
                     # range whose destination never adopted (rc_id,
                     # why, tick) and resumed serving it
    "autopilot_act", # autopilot actuation applied on this server
                     # (act, plus actuator-specific fields like reason/
                     # api_max_batch/pipeline, tick) — the policy
                     # tier's instant on the exported ctrl track
    "transport_handshake_fail",
                     # a p2p dialer never completed the id handshake
                     # (error) — one stray is a port scan; a stream of
                     # them is codec skew after a partial upgrade, and
                     # without the record the mesh silently never forms
)
_EVENT_SET = frozenset(EVENT_TYPES)

SCHEMA_VERSION = 2


class FlightRecorder:
    """Fixed-size, lock-cheap ring of typed monotonic-stamped events.

    ``enabled=False`` turns every ``record`` into one attribute read —
    the recorder-off variant the tier-2f overhead gate compares against.
    ``capacity`` bounds memory AND dump size; overflow drops oldest —
    but each event type additionally keeps ``reserve_per_type`` newest
    events of its own in a side ring, so rare types survive a flood of
    hot ones.  A dump is the seq-ordered union (main ∪ reserves,
    deduped), which for a single-type stream is exactly the main ring.
    """

    def __init__(self, capacity: int = 8192, enabled: bool = True,
                 me: int = -1, reserve_per_type: Optional[int] = None):
        self.capacity = max(16, int(capacity))
        self.enabled = bool(enabled)
        self.me = me
        self.reserve_per_type = (
            max(8, self.capacity // 64) if reserve_per_type is None
            else max(1, int(reserve_per_type))
        )
        self._lock = threading.Lock()
        self._buf: deque = deque(maxlen=self.capacity)
        self._seq = 0  # events ever recorded (>= len(_buf))
        # per-type reservations + lifetime counts (schema v2 accounting)
        self._reserve: Dict[str, deque] = {}
        self._recorded: Dict[str, int] = {}
        # publish_drops cursor: per-type drops already mirrored into the
        # registry (drop counts are monotone — an evicted event never
        # returns — so the delta is always >= 0)
        self._published: Dict[str, int] = {}
        # incarnation floor: a crash-restarted server gets a FRESH
        # recorder (and restarts its tick counter, reusing wire seqs),
        # so the exporter uses this birth stamp to refuse pairing the
        # new incarnation's frames against a peer's stale rx events
        self._t_start_us = int(time.monotonic() * 1e6)

    # -- write side (every hub seam; hot-path safe) -------------------------
    def record(self, etype: str, **fields: Any) -> None:
        """Append one event.  ``etype`` must be a declared
        :data:`EVENT_TYPES` name — an undeclared type is a contributor
        bug and fails loudly, same policy as the device metric lanes."""
        if not self.enabled:
            return
        if etype not in _EVENT_SET:
            raise KeyError(etype)
        with self._lock:
            # stamp INSIDE the lock: a pre-lock stamp lets a preempted
            # writer append behind a later-stamped peer, breaking the
            # ring's oldest-first stamp order that dumps/tails rely on
            t_us = int(time.monotonic() * 1e6)
            ev = (self._seq, t_us, etype, fields)
            self._buf.append(ev)
            res = self._reserve.get(etype)
            if res is None:
                res = self._reserve[etype] = deque(
                    maxlen=self.reserve_per_type
                )
            res.append(ev)
            self._recorded[etype] = self._recorded.get(etype, 0) + 1
            self._seq += 1

    # -- read side -----------------------------------------------------------
    def dump(self, last_n: Optional[int] = None) -> Dict[str, Any]:
        """JSON-able snapshot: the retained events (oldest first, trimmed
        to ``last_n`` newest when given) plus the drop accounting that
        makes truncation visible."""
        with self._lock:
            events = list(self._buf)
            total = self._seq
            recorded = dict(self._recorded)
            reserves = [list(r) for r in self._reserve.values()]
        # union the per-type reserves in (dedup by ring seq): a rare
        # type washed out of the main ring survives in its reserve, so
        # the dump keeps at least the newest few of EVERY type
        seen = {ev[0] for ev in events}
        extra = [
            ev for ring in reserves for ev in ring if ev[0] not in seen
        ]
        if extra:
            events = sorted(events + extra)
        if last_n is not None:
            n = int(last_n)
            # n <= 0 means "metadata only" (events[-0:] would be ALL)
            events = events[-n:] if n > 0 else []
        retained: Dict[str, int] = {}
        for ev in events:
            retained[ev[2]] = retained.get(ev[2], 0) + 1
        # invariant: sum(dropped_by_type.values()) == dropped — every
        # recorded event has exactly one type, so the per-type ledger
        # always reconciles against the scalar drop count
        dropped_by_type = {
            t: recorded[t] - retained.get(t, 0)
            for t in sorted(recorded)
            if recorded[t] - retained.get(t, 0) > 0
        }
        return {
            "v": SCHEMA_VERSION,
            "me": self.me,
            "t_start_us": self._t_start_us,
            "count": total,
            "dropped": total - len(events),
            "recorded_by_type": {t: recorded[t] for t in sorted(recorded)},
            "dropped_by_type": dropped_by_type,
            "t_dump_us": int(time.monotonic() * 1e6),
            # "n" is the ring's own event counter ("seq" stays free for
            # the frame events' wire sequence field)
            "events": [
                {"n": seq, "t_us": t_us, "type": etype, **fields}
                for seq, t_us, etype, fields in events
            ],
        }

    def publish_drops(self, registry) -> None:
        """Mirror per-type drop counts into the metrics registry as
        ``trace_dropped_total{type=...}`` counters (scrape-time path —
        called from ``metrics_snapshot``, never the record hot path).
        Only NEW drops since the last publish are added, so repeated
        scrapes don't double-count.  Drops here are main-ring evictions
        net of reserve survival — the events a dump can no longer show."""
        with self._lock:
            retained: Dict[str, int] = {}
            for ev in self._buf:
                retained[ev[2]] = retained.get(ev[2], 0) + 1
            # reserve events absent from the main ring still ride
            # dumps, so they count as retained, not dropped
            reserve_extra: Dict[str, int] = {}
            main_seqs = {ev[0] for ev in self._buf}
            for t, ring in self._reserve.items():
                reserve_extra[t] = sum(
                    1 for ev in ring if ev[0] not in main_seqs
                )
            deltas = []
            for t, rec in self._recorded.items():
                dropped = rec - retained.get(t, 0) - reserve_extra.get(t, 0)
                new = dropped - self._published.get(t, 0)
                if new > 0:
                    self._published[t] = dropped
                    deltas.append((t, new))
        for t, new in deltas:
            registry.counter_add("trace_dropped_total", new, type=t)

    def tail(self, n: int = 64) -> List[str]:
        """The last ``n`` events rendered one per line — the
        crash-report attachment format (test_cluster supervisor)."""
        n = int(n)
        if n <= 0:
            return []
        with self._lock:
            events = list(self._buf)[-n:]
        return [
            f"#{seq} t={t_us}us {etype} " + " ".join(
                f"{k}={fields[k]}" for k in sorted(fields)
            )
            for seq, t_us, etype, fields in events
        ]
