"""Live keyspace resharding: range split/merge over the ctrl plane.

The G axis hashes keys to groups (``ServerReplica.group_of``); this module
turns that static placement into a *live* one.  A ``RangeChange`` installs a
key range ``[start, end)`` into an explicit destination group using the same
revoke-then-adopt discipline as ConfChange:

1. **Seal** — every replica stops accepting new ops for the range the moment
   the manager's ``range_change`` ctrl fan-out lands (front-door sheds; the
   shed is client-visible backpressure, never a lost ack).
2. **Barrier** — the adopting leader waits until the source group's log has
   no voted-but-unapplied write to the range (the commit-slot barrier), so
   the handoff snapshot is complete.
3. **Adopt** — a range-filtered KV snapshot plus write-slot watermarks and
   per-group apply floors ride an ``adopt`` command *through the destination
   group's own log*, so adoption is itself replicated, recoverable, and
   ordered against destination traffic.  Once applied, the range serves from
   the destination and the proposer announces installation to the manager,
   which re-announces to proxies/late joiners (the ConfChange re-announce
   path).

Split vs merge is pure policy: both lower to the same install op; a split
moves a hot sub-range off its hash-home, a merge moves a cold installed
range back.  ``RangeHeat`` + ``ResharderPolicy`` close the loop from
per-range heat telemetry to ctrl-plane ``range_change`` requests.

Related work: compartmentalized SMR (arxiv 2012.15762) — the proxy/shard
decomposition this subsystem's routing rides on.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils.errors import SummersetError
from ..utils.keyrange import KeyRangeMap


def single_key_range(key: str) -> Tuple[str, str]:
    """The smallest half-open range containing exactly ``key``."""
    return key, key + "\x00"


@dataclasses.dataclass(frozen=True)
class RangeChange:
    """One validated range install request (split or merge).

    ``op`` is advisory ("split" or "merge") — both lower to the same
    install; it selects which counter (``reshard_splits`` /
    ``reshard_merges``) the adoption bumps.  ``end is None`` means
    unbounded.  ``rc_id`` is assigned by the manager (monotone per
    manager lifetime) and is the idempotency key for seal/adopt.
    """

    op: str
    start: str
    end: Optional[str]
    dst_group: int
    rc_id: int = 0

    def validate(self) -> None:
        if self.op not in ("split", "merge"):
            raise SummersetError(f"unknown range op {self.op!r}")
        if not isinstance(self.start, str):
            raise SummersetError("range start must be a string key")
        if self.end is not None and self.end <= self.start:
            raise SummersetError(
                f"invalid key range [{self.start!r}, {self.end!r})")
        if not isinstance(self.dst_group, int) or self.dst_group < 0:
            raise SummersetError(
                f"invalid dst_group {self.dst_group!r}")

    def contains(self, key: str) -> bool:
        return key >= self.start and (self.end is None or key < self.end)

    def as_dict(self) -> Dict[str, Any]:
        return {
            "rc_id": self.rc_id, "op": self.op, "start": self.start,
            "end": self.end, "dst_group": self.dst_group,
        }

    @staticmethod
    def from_payload(payload: dict) -> "RangeChange":
        ch = RangeChange(
            op=str(payload.get("op", "split")),
            start=payload.get("start", ""),
            end=payload.get("end"),
            dst_group=payload.get("dst_group", 0),
            rc_id=int(payload.get("rc_id", 0)),
        )
        ch.validate()
        return ch


class RangeTable:
    """Installed range overrides: key range -> owning group.

    Wraps a :class:`KeyRangeMap` (later installs overwrite overlapped
    portions, rangemap semantics) plus the install entries in ``rc_id``
    order for re-announce / snapshot meta.  Lookup misses fall back to
    the caller's hash placement — the table only ever holds overrides.
    """

    def __init__(self):
        self._map: KeyRangeMap[dict] = KeyRangeMap()
        self._entries: Dict[int, dict] = {}

    def install(self, entry: dict) -> bool:
        """Install an adopted range; idempotent per rc_id.  Returns True
        if this call changed the table."""
        rc_id = int(entry["rc_id"])
        if rc_id in self._entries:
            return False
        self._entries[rc_id] = dict(entry)
        self._map.insert(entry["start"], entry.get("end"), dict(entry))
        return True

    def lookup(self, key: str) -> Optional[dict]:
        return self._map.get(key)

    def group_for(self, key: str) -> Optional[int]:
        e = self._map.get(key)
        return None if e is None else int(e["group"])

    def has(self, rc_id: int) -> bool:
        return int(rc_id) in self._entries

    def entries(self) -> List[dict]:
        """All install entries in rc_id (i.e. adoption) order."""
        return [dict(self._entries[k]) for k in sorted(self._entries)]

    def __len__(self) -> int:
        return len(self._map)


class RangeHeat:
    """Bounded per-key op-count telemetry at an ingress seam.

    Key cardinality is capped; once full, new keys fold into a spill
    bucket so the hot set stays exact while the tail stays bounded.
    Scraped as labeled ``range_heat`` gauges (top-K) plus a bare total.
    """

    SPILL = "__other__"

    def __init__(self, cap: int = 512):
        self.cap = int(cap)
        self._counts: Dict[str, int] = {}

    def note(self, key: str, n: int = 1) -> None:
        c = self._counts
        if key in c:
            c[key] += n
        elif len(c) < self.cap:
            c[key] = n
        else:
            c[self.SPILL] = c.get(self.SPILL, 0) + n

    def top(self, k: int = 8) -> List[Tuple[str, int]]:
        items = [(key, n) for key, n in self._counts.items()
                 if key != self.SPILL]
        items.sort(key=lambda t: (-t[1], t[0]))
        return items[:k]

    def total(self) -> int:
        return sum(self._counts.values())

    def clear(self) -> None:
        self._counts.clear()


class ResharderPolicy:
    """Heat-driven placement: split hot keys off their hash-home, merge
    cold installed ranges back.

    Pure decision logic — the caller scrapes heat, feeds ``decide``, and
    issues the returned :class:`RangeChange` requests over the ctrl
    plane.  One decision per call keeps cutovers serialized (each seals
    its range until adopted; flooding seals would just shed).

    When an autopilot owns this policy (host/autopilot.py) it installs
    ``budget_gate``: ``decide`` consults it with the candidate
    destination group BEFORE committing to a change, so reshard
    decisions answer to the autopilot's per-window actuation budget and
    per-group change cap instead of firing independently — a heat spike
    can no longer race a leader re-placement on the same group.  A
    refused candidate is left untouched (``_moved`` unchanged), so the
    same decision stays available next call.
    """

    def __init__(
        self,
        num_groups: int,
        hash_group,  # Callable[[str], int] — the cluster's hash placement
        hot_frac: float = 0.25,
        cold_frac: float = 0.02,
        min_total: int = 20,
        budget_gate: Optional[Callable[[int], bool]] = None,
    ):
        self.G = int(num_groups)
        self.hash_group = hash_group
        self.hot_frac = float(hot_frac)
        self.cold_frac = float(cold_frac)
        self.min_total = int(min_total)
        self.budget_gate = budget_gate
        self._moved: Dict[str, int] = {}  # key -> installed dst group

    def decide(
        self, heat: Dict[str, int],
    ) -> Optional[RangeChange]:
        """One split or merge decision from a heat scrape, or None.

        Splits take priority: the hottest not-yet-moved key drawing at
        least ``hot_frac`` of total heat moves to the next group round-
        robin from its hash-home.  Otherwise the coldest already-moved
        key below ``cold_frac`` merges back to its hash-home.
        """
        total = sum(heat.values())
        if total < self.min_total or self.G < 2:
            return None
        ranked = sorted(
            ((k, n) for k, n in heat.items()
             if k != RangeHeat.SPILL),
            key=lambda t: (-t[1], t[0]),
        )
        for key, n in ranked:
            if key in self._moved:
                continue
            if n < self.hot_frac * total:
                break  # ranked: nothing below is hotter
            start, end = single_key_range(key)
            dst = (self.hash_group(key) + 1) % self.G
            if self.budget_gate is not None \
                    and not self.budget_gate(dst):
                break  # budget-refused; candidate stays for next call
            self._moved[key] = dst
            return RangeChange("split", start, end, dst)
        for key, n in sorted(ranked, key=lambda t: (t[1], t[0])):
            if key not in self._moved:
                continue
            if n > self.cold_frac * total:
                continue
            home = self.hash_group(key)
            start, end = single_key_range(key)
            if self.budget_gate is not None \
                    and not self.budget_gate(home):
                continue  # budget-refused; candidate stays for next call
            # forget the key entirely: a merged-back key that re-heats
            # must be eligible for a future split (leaving it in _moved
            # mapped to its hash-home would pin it forever)
            del self._moved[key]
            return RangeChange("merge", start, end, home)
        return None
