"""StorageHub: durable WAL/snapshot logger behind a submit/result queue.

Parity: reference ``src/server/storage.rs`` — a hub owning a logger task;
actions ``Read/Write/Append/Truncate/Discard`` over 8-byte length-prefixed
entries in a flat file, with optional fsync (``LogAction`` storage.rs:25-45,
``LogResult`` :49-70, logger task :192-510).  The hot file path is the
native C++ backend (``native/wal.cpp``) driven by a worker thread; a pure-
Python mirror keeps toolchain-less hosts working.  Entries are pickled
Python objects, mirroring the reference's bincode-serialized ``Ent``.

WAL record shapes (written by ``host/server.py``, replayed at recovery):

- ``("vote", g, rec)`` — durable acceptor row for group ``g``: one int
  per ``DURABLE_SCALARS`` field, one list per ``DURABLE_WINDOWS`` lane,
  plus payloads for newly voted value ids — ``rec["pp"]`` maps vid ->
  full ReqBatch (non-coded protocols and CRaft full-copy fallback), and
  ``rec["cw"]`` maps vid -> ``(data_len, {shard id: [L] int32})`` shard
  subsets (the codeword plane: each voter logs the slice its vote stands
  for; a recovered quorum's shards rebuild committed values by gossip).
- ``(g, slot, vid, batch)`` — exec-time apply record (KV replay source).
- ``("eapply", g, row, col, vid, batch)`` — EPaxos exec record, replayed
  in logged (= execution) order.
"""

from __future__ import annotations

import ctypes
import dataclasses
import os
import pickle
import queue
import struct
import threading
import time
from typing import Any, Optional, Tuple

from ..native import load_wal
from ..utils.errors import SummersetError

_LEN = struct.Struct("<Q")

# background-channel action ids (the pipelined group-commit plane): a
# fire-and-forget append delivers NO result; a flush carries its token
_BG_APPEND = "__bg_append__"
_BG_FLUSH = "__bg_flush__"


@dataclasses.dataclass
class LogAction:
    """One logger action (parity: ``LogAction``, storage.rs:25-45)."""

    kind: str        # read | write | append | truncate | discard | sync
    entry: Any = None         # write/append payload (any picklable object)
    offset: int = 0           # read/write/truncate/discard target offset
    keep: int = 0             # discard: bytes of header to keep
    sync: bool = False        # fsync after mutating


@dataclasses.dataclass
class LogResult:
    """Logger completion (parity: ``LogResult``, storage.rs:49-70)."""

    kind: str
    entry: Any = None           # read: the decoded entry (None past end)
    end_offset: int = 0         # read/write/append: entry end offset
    offset_ok: bool = True      # write/truncate/discard validity
    now_size: int = 0           # truncate/discard: resulting log size


class _PyWal:
    """Pure-Python fallback mirror of native/wal.cpp."""

    def __init__(self, path: str):
        # r+b (not a+b): O_APPEND would ignore seeks on write
        if not os.path.exists(path):
            open(path, "wb").close()
        self.f = open(path, "r+b")
        self.f.seek(0, os.SEEK_END)
        self.size = self.f.tell()

    def append(self, body: bytes, sync: bool) -> int:
        self.f.seek(self.size)
        self.f.write(_LEN.pack(len(body)) + body)
        self.size += 8 + len(body)
        self.f.flush()
        if sync:
            os.fdatasync(self.f.fileno())
        return self.size

    def write_at(self, off: int, body: bytes, sync: bool) -> int:
        self.f.seek(off)
        self.f.write(_LEN.pack(len(body)) + body)
        end = off + 8 + len(body)
        self.size = max(self.size, end)
        self.f.flush()
        if sync:
            os.fdatasync(self.f.fileno())
        return end

    def read(self, off: int) -> Optional[Tuple[bytes, int]]:
        if off + 8 > self.size:
            return None
        self.f.seek(off)
        (length,) = _LEN.unpack(self.f.read(8))
        if off + 8 + length > self.size:
            return None
        return self.f.read(length), off + 8 + length

    def truncate(self, off: int, sync: bool) -> bool:
        if off > self.size:
            return False
        self.f.truncate(off)
        self.size = off
        if sync:
            self.f.flush()
            os.fdatasync(self.f.fileno())
        return True

    def sync(self) -> None:
        self.f.flush()
        os.fdatasync(self.f.fileno())

    def discard(self, off: int, keep: int, sync: bool) -> bool:
        if off < keep or off > self.size:
            return False
        self.f.seek(off)
        tail = self.f.read(self.size - off)
        self.f.seek(keep)
        self.f.write(tail)
        self.f.truncate(keep + len(tail))
        self.size = keep + len(tail)
        self.f.flush()
        if sync:
            os.fdatasync(self.f.fileno())
        return True

    def close(self):
        self.f.close()


class _NativeWal:
    """ctypes facade over native/wal.cpp with the same method surface."""

    def __init__(self, lib, path: str):
        self.lib = lib
        self.h = lib.wal_open(path.encode())
        if not self.h:
            raise SummersetError(f"wal_open failed for {path}")

    @property
    def size(self) -> int:
        return self.lib.wal_size(self.h)

    def append(self, body: bytes, sync: bool) -> int:
        end = self.lib.wal_append(self.h, body, len(body), int(sync))
        if end == 0:
            raise SummersetError("wal_append failed")
        return end

    def write_at(self, off: int, body: bytes, sync: bool) -> int:
        end = self.lib.wal_write_at(self.h, off, body, len(body), int(sync))
        if end == 0:
            raise SummersetError("wal_write_at failed")
        return end

    def read(self, off: int) -> Optional[Tuple[bytes, int]]:
        cap = 1 << 16
        while True:
            buf = (ctypes.c_uint8 * cap)()
            n = self.lib.wal_read(self.h, off, buf, cap)
            if n == -1:
                return None
            if n == -2:
                cap *= 4
                continue
            return bytes(buf[: int(n)]), off + 8 + int(n)

    def truncate(self, off: int, sync: bool) -> bool:
        return self.lib.wal_truncate(self.h, off, int(sync)) == 0

    def sync(self) -> None:
        # truncate-to-current-size with sync=1 is a pure fsync (the
        # native surface has no separate sync entry point)
        if self.lib.wal_truncate(self.h, self.size, 1) != 0:
            raise SummersetError("wal fsync failed")

    def discard(self, off: int, keep: int, sync: bool) -> bool:
        return self.lib.wal_discard(self.h, off, keep, int(sync)) == 0

    def close(self):
        # idempotent: a double close would hand the native layer a freed
        # handle and SIGABRT the whole process (the shutdown path can be
        # reached from both the replica loop and an external stop)
        if self.h:
            self.lib.wal_close(self.h)
            self.h = None


class StorageHub:
    """Durable logger hub: submit actions, collect results in order.

    The channel-based API mirrors the reference hub
    (``submit_action``/``get_result``, storage.rs:137-190); the logger
    thread owns the file, like the reference's spawned logger task.
    """

    def __init__(self, path: str, prefer_native: bool = True,
                 registry=None, flight=None):
        lib = load_wal() if prefer_native else None
        self.backend = _NativeWal(lib, path) if lib else _PyWal(path)
        self.native = lib is not None and prefer_native
        self._in: queue.Queue = queue.Queue()
        self._out: queue.Queue = queue.Queue()
        self._stop_lock = threading.Lock()
        self._stopped = False
        # background group commit (the pipelined tick loop's durability
        # fence): fire-and-forget appends + token-stamped sync points.
        # The logger thread is a FIFO, so a token enqueued after a run
        # of appends covers exactly those appends; completion (or the
        # first error — a torn append, an EIO fsync) is published under
        # the condition and re-raised at wait_flush, BEFORE any frame or
        # reply gated on the token can leave the replica.
        self._flush_cv = threading.Condition()
        self._flush_next = 0          # tokens issued
        self._flush_done = 0          # tokens completed (monotonic)
        self._bg_error: Optional[BaseException] = None
        # telemetry seam (host/telemetry.MetricsRegistry): fsync latency
        # is THE durability cost — one sync point covers every append
        # since the last (group commit), so batch size rides along
        self.registry = registry
        # graftscope seam (host/tracing.FlightRecorder): wal_append /
        # wal_fsync events on the logger thread — the storage track of
        # the exported timeline (fsync spans carry batch + duration)
        self.flight = flight
        # gray-failure seam (host/health.py HealthScorer): per-sync
        # durability latency feeds the scorer's slow_disk / mem_pressure
        # signals (attached by the server after construction)
        self.health = None
        self._since_sync = 0
        self._unsynced_bytes = 0  # mem_pressure bounded-buffer meter
        # disk fault injection (host/nemesis.py): a mutable spec consulted
        # by the logger thread before each action.  None = no faults.
        self._faults: Optional[dict] = None
        self._thread = threading.Thread(target=self._logger, daemon=True)
        self._thread.start()

    # -- fault injection -----------------------------------------------------
    def set_faults(self, spec: Optional[dict], seed: int = 0) -> None:
        """Arm (or clear, with ``spec=None``) disk-fault injection:

        - ``{"torn": 1}`` — the next append is torn: the record's bytes
          are only partially persisted (a crash mid-write) and the
          backend goes sticky-dead, so every later action fails too —
          one tear is one crash, by construction.  The replica's
          group-commit fsync then raises, it crashes before any gated
          ack leaves, and recovery must detect + truncate the tear
          (``server._recover_from_wal``).
        - ``{"fsync_fail": n}`` — the next ``n`` sync points fail (EIO-
          style); the durability gate turns this into a crash as well.
        - ``{"slow": f}`` — fail-slow ``slow_disk``: every durability
          point (and sync append) takes ``f``x its measured time (floor
          500us), paid as a sleep INSIDE the timed region so the
          ``wal_fsync_us`` histogram — the health scorer's slow_disk
          signal — sees the limp.  Duration-armed by the nemesis heal
          action, or count-armed with ``{"slow": f, "slow_count": n}``
          (self-clears after ``n`` inflated sync points, like
          ``wal_fsync``).
        - ``{"mem": cap}`` — fail-slow ``mem_pressure``: a bounded
          allocator for the WAL write-back buffer.  Un-synced appended
          bytes beyond ``cap`` force an inline durability point plus a
          direct-reclaim stall (``mem_stall`` seconds, default 40ms)
          before the append proceeds — a tiny buffer turns group commit
          into constant forced fsyncs, the classic memory-pressure limp.

        ``seed`` is accepted for interface symmetry with
        ``TransportHub.set_faults`` (the WAL faults are count- or
        duration-armed, not probabilistic — a tear either happens at a
        schedule point or not).
        """
        del seed
        self._faults = dict(spec) if spec else None

    # -- channel API ---------------------------------------------------------
    def submit_action(self, action_id: Any, action: LogAction) -> None:
        self._in.put((action_id, action))

    def get_result(self, timeout: Optional[float] = None):
        """Blocking next (action_id, LogResult)."""
        return self._out.get(timeout=timeout)

    def do_sync_action(self, action: LogAction) -> LogResult:
        """Convenience: run one action synchronously (reference
        ``do_sync_action`` pattern, used by recovery replay)."""
        self.submit_action(None, action)
        aid, res = self.get_result()
        assert aid is None
        return res

    # -- background group commit (pipelined durability fence) ---------------
    def append_nowait(self, entry: Any) -> None:
        """Fire-and-forget unsynced append on the logger thread.  No
        result is delivered; a failure (torn write, dead device) is
        latched as the hub's background error and re-raised by the NEXT
        ``wait_flush`` — the records it covered never became durable, so
        the fence gating their acks must fail, not silently pass."""
        self._in.put((_BG_APPEND, LogAction("append", entry=entry,
                                            sync=False)))

    def flush_token(self) -> int:
        """Enqueue a background group-commit sync point covering every
        append submitted before it (the logger is a FIFO) and return a
        token for :meth:`wait_flush`.  The fsync runs on the logger
        thread while the caller overlaps other work — the pipelined
        loop's durability fence."""
        with self._flush_cv:
            self._flush_next += 1
            token = self._flush_next
        self._in.put(((_BG_FLUSH, token), LogAction("sync")))
        return token

    def poll_flush(self, token: int) -> bool:
        """Non-blocking fence probe: True iff the ``token``'s sync point
        already completed.  Raises the latched background error exactly
        like :meth:`wait_flush` — a failed group commit must crash the
        caller at the first probe, not linger behind a False."""
        with self._flush_cv:
            if self._bg_error is not None:
                raise SummersetError(
                    f"WAL background group commit failed: {self._bg_error}"
                )
            return self._flush_done >= token

    def wait_flush(self, token: int, timeout: Optional[float] = None) -> None:
        """Block until the ``token``'s sync point completed.  Raises the
        first background error (failed fsync OR any earlier failed
        background append) — the caller must treat that as fatal before
        releasing anything gated on the token.  Raises
        :class:`SummersetError` on timeout."""
        with self._flush_cv:
            ok = self._flush_cv.wait_for(
                lambda: self._bg_error is not None
                or self._flush_done >= token,
                timeout=timeout,
            )
            if self._bg_error is not None:
                raise SummersetError(
                    f"WAL background group commit failed: {self._bg_error}"
                )
            if not ok:
                raise SummersetError(
                    f"WAL flush token {token} timed out after {timeout}s"
                )

    def stop(self) -> None:
        # idempotent + race-safe: the replica loop's own shutdown and an
        # external harness stop can both reach here concurrently; a
        # second backend.close() on the native WAL would abort the
        # process (wal.cpp frees the handle)
        with self._stop_lock:
            if self._stopped:
                return
            self._stopped = True
        self._in.put(None)
        self._thread.join(timeout=5)
        self.backend.close()

    @property
    def size(self) -> int:
        return self.backend.size

    # -- logger thread -------------------------------------------------------
    def _inject_fault(self, a: LogAction) -> None:
        """Raise the armed fault for this action, mutating the disk state
        the way a real crash would (runs on the logger thread, which owns
        the backend — same single-writer discipline as normal actions)."""
        f = self._faults
        if not f:
            return
        if f.get("dead"):
            raise OSError("injected: WAL device dead after torn write")
        if a.kind == "append" and f.get("torn", 0) > 0:
            f["torn"] -= 1
            b = self.backend
            body = pickle.dumps(a.entry)
            end = b.append(body, False)
            # tear the record: keep the header + a body prefix on disk,
            # exactly what an 8-byte-at-a-time crash leaves behind
            b.truncate(end - max(1, len(body) // 2), True)
            f["dead"] = True
            raise OSError(
                "injected: torn append (crash mid-record write)"
            )
        if f.get("fsync_fail", 0) > 0 and (
            a.kind == "sync" or a.sync
        ):
            f["fsync_fail"] -= 1
            raise OSError("injected: fsync failed (EIO)")

    def _slow_stall(self, elapsed: float, floor: float,
                    is_sync: bool = True) -> float:
        """Seconds of injected ``slow_disk`` stall for an op that took
        ``elapsed`` seconds (0.0 when the fault is unarmed).  Sync
        points decrement the optional ``slow_count`` arm, which
        self-clears at zero (count-armed like ``wal_fsync``)."""
        f = self._faults
        if not f:
            return 0.0
        factor = float(f.get("slow", 0.0) or 0.0)
        if factor <= 1.0:
            return 0.0
        # optional floor override: on tmpfs-backed test dirs the
        # measured fsync is ~100us, so a pure multiplicative limp would
        # be invisible — "slow_floor" pins the limping disk's per-op
        # cost the way a real degraded device pins its minimum latency
        floor = float(f.get("slow_floor", floor) or floor)
        cnt = f.get("slow_count")
        if cnt is not None and is_sync:
            if cnt <= 0:
                f.pop("slow", None)
                return 0.0
            f["slow_count"] = cnt - 1
        return (factor - 1.0) * max(elapsed, floor)

    def _sync_point(self, fn):
        """Run a durability point, timing it and closing out the group-
        commit batch opened by the appends since the last sync.  The
        injected slow_disk inflation sleeps INSIDE the timed region so
        the wal_fsync_us histogram reports the disk the replica actually
        has — that histogram is the health plane's slow_disk signal."""
        reg = self.registry
        t0 = time.monotonic()
        res = fn()
        stall = self._slow_stall(time.monotonic() - t0, 500e-6)
        if stall > 0:
            time.sleep(stall)
        self._unsynced_bytes = 0
        if reg is None and self.flight is None and self.health is None:
            return res
        dur = time.monotonic() - t0
        if reg is not None:
            reg.observe_s("wal_fsync_us", dur)
            reg.observe("wal_group_commit_batch", self._since_sync)
        if self.health is not None:
            self.health.note_fsync(dur)
        if self.flight is not None:
            self.flight.record(
                "wal_fsync", dur_us=int(dur * 1e6),
                batch=self._since_sync,
            )
        self._since_sync = 0
        return res

    def _handle(self, a: LogAction) -> LogResult:
        self._inject_fault(a)
        b = self.backend
        if a.kind == "read":
            got = b.read(a.offset)
            if got is None:
                return LogResult("read", entry=None, end_offset=a.offset,
                                 offset_ok=False)
            body, end = got
            return LogResult("read", entry=pickle.loads(body),
                             end_offset=end)
        if a.kind == "append":
            if self.registry is not None:
                self.registry.counter_add("wal_appends_total")
            self._since_sync += 1
            if self.flight is not None:
                self.flight.record("wal_append", sync=bool(a.sync))
            # serialize OUTSIDE the timed region: wal_fsync_us must
            # measure durability (write + fsync), not pickling CPU
            data = pickle.dumps(a.entry)
            f = self._faults
            cap = int(f.get("mem", 0) or 0) if f else 0
            if cap > 0 and self._unsynced_bytes + len(data) > cap:
                # mem_pressure: the bounded write-back buffer is full —
                # reclaim by forcing an inline durability point, plus
                # the allocator's direct-reclaim stall (tens of ms is
                # what real memory pressure costs a dirty-page writer).
                # Timed like any sync point, so the per-tick durability
                # cost the health beacon reports reflects the limp.
                stall = float(f.get("mem_stall", 0.04) or 0.0)
                self._sync_point(
                    lambda: (b.sync(), time.sleep(stall))
                )
            if a.sync:
                end = self._sync_point(lambda: b.append(data, True))
            else:
                end = b.append(data, False)
                self._unsynced_bytes += len(data)
                stall = self._slow_stall(0.0, 50e-6, is_sync=False)
                if stall > 0:
                    time.sleep(stall)
            return LogResult("append", end_offset=end)
        if a.kind == "write":
            if a.offset > b.size:
                return LogResult("write", offset_ok=False)
            end = b.write_at(a.offset, pickle.dumps(a.entry), a.sync)
            return LogResult("write", end_offset=end)
        if a.kind == "truncate":
            ok = b.truncate(a.offset, a.sync)
            return LogResult("truncate", offset_ok=ok, now_size=b.size)
        if a.kind == "discard":
            ok = b.discard(a.offset, a.keep, a.sync)
            return LogResult("discard", offset_ok=ok, now_size=b.size)
        if a.kind == "sync":
            # group commit: fsync once after a batch of sync=False
            # appends (the reference batches WAL writes per batch too —
            # one durability point per ReqBatch, not per entry)
            self._sync_point(b.sync)
            return LogResult("sync", now_size=b.size)
        raise SummersetError(f"unknown log action kind {a.kind}")

    def _logger(self) -> None:
        while True:
            item = self._in.get()
            if item is None:
                return
            action_id, action = item
            # background channel: no result queue round-trip — errors
            # latch into _bg_error (sticky) and surface at wait_flush,
            # the durability fence the pipelined loop blocks on
            if action_id == _BG_APPEND:
                try:
                    self._handle(action)
                except Exception as e:
                    with self._flush_cv:
                        if self._bg_error is None:
                            self._bg_error = e
                        self._flush_cv.notify_all()
                continue
            if isinstance(action_id, tuple) and action_id[0] == _BG_FLUSH:
                token = action_id[1]
                try:
                    self._handle(action)
                    with self._flush_cv:
                        self._flush_done = max(self._flush_done, token)
                        self._flush_cv.notify_all()
                except Exception as e:
                    with self._flush_cv:
                        if self._bg_error is None:
                            self._bg_error = e
                        self._flush_cv.notify_all()
                continue
            try:
                res = self._handle(action)
            except Exception as e:  # surface backend errors to the caller
                res = LogResult(action.kind, offset_ok=False, entry=e)
            self._out.put((action_id, res))
