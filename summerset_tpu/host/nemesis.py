"""Deterministic nemesis engine: seeded fault schedules across planes.

One ``FaultPlan`` — a seed-generated list of timed fault events — compiles
into coordinated actions on all three seams of the stack:

1. **Device plane** (``core/netmodel.ControlInputs``): ``compile_device``
   lowers the plan to per-tick ``alive``/``link_up`` mask sequences for
   ``Engine.run_ticks`` — the whole schedule executes inside one
   ``lax.scan`` with zero host involvement, bit-identical per seed.
2. **Host message plane** (``host/transport.py`` + the
   ``utils/safetcp.FrameFaults`` shim): partitions, asymmetric link
   faults, iid drop, duplication, and added delay on a live cluster's
   p2p mesh, installed through the manager control plane
   (``CtrlRequest("inject_faults")`` → per-server ``fault_ctl``).
3. **Disk plane** (``host/storage.StorageHub.set_faults``): torn tail
   records and failing fsyncs; the replica's durability gate turns these
   into crashes, and its supervisor restart exercises WAL torn-tail
   truncation plus manager id reclamation.

Crash/restart and pause/resume ride the existing manager orchestration
(``reset_servers`` / ``pause_servers``), i.e. real process control.

Determinism contract: ``FaultPlan.generate(seed, ...)`` draws only from
``random.Random(seed)``, so the same seed always yields a byte-identical
``timeline()`` (and identical compiled device masks) — every robustness
bug found under a schedule is a one-line repro (``--seed N``).  On a live
cluster the *schedule* is deterministic while OS-level interleaving stays
real, the same split a seeded Jepsen nemesis gives you.

Related work: compartmentalized SMR (arxiv 2012.15762) concentrates bugs
at plane seams; this engine stresses our three seams under one clock.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..host.messages import CtrlRequest
from ..utils.errors import SummersetError
from ..utils.logging import pf_info, pf_logger, pf_warn

logger = pf_logger("nemesis")

# every fault class the engine knows how to schedule; generation defaults
# to the full set, callers narrow it (e.g. device-only plans skip wal_*)
ALL_CLASSES = (
    "crash",       # durable crash-restart (manager-orchestrated)
    "device_reset",  # durable DEVICE crash: down for `duration`, then the
                   # state row is rebuilt from the kernel's declared
                   # durable leaves only (volatile rows zeroed) — the
                   # host lowering is a manager durable reset, so both
                   # planes lose volatile state the same way
    "pause",       # SIGSTOP-style freeze + resume after `duration`
    "partition",   # symmetric split: targets vs the rest
    "isolate",     # cut each target from everyone
    "one_way",     # asymmetric: src->dst down, reverse fine
    "drop",        # iid per-frame loss at prob `arg` on targets' egress
    "delay",       # +`arg` seconds one-way ingress delay at targets
    "dup",         # per-frame duplication at prob `arg`
    "wal_torn",    # next WAL append tears mid-record; replica crashes
    "wal_fsync",   # next `arg` fsyncs fail; durability gate crashes
    "clock_skew",  # targets' tick clocks run at rate `arg` < 1 (device:
                   # duty-cycled alive masks; host: tick_interval / arg)
    "conf_change",  # drive a client ConfChange (responders := targets)
                   # through the manager relay WHILE other faults play —
                   # the QuorumLeases/Bodega revoke-then-adopt barrier's
                   # adversarial coverage; conf-less protocols answer
                   # with an explicit failure (the reply path is still
                   # exercised)
    "take_snapshot",  # compaction on the serving path: targets snapshot
                   # + WAL-compact mid-schedule; arg=1 arms a crash
                   # point between the snapshot write and the WAL
                   # truncate (recovery must reconcile new snapshot +
                   # old WAL)
    # -- gray failures (fail-slow: the victim stays alive enough to hold
    # leadership/leases while tanking the group; host/health.py is the
    # detection plane, voluntary leader demotion the mitigation) --
    "slow_disk",   # StorageHub fsync/append latency inflated x `arg`
                   # on targets for `duration` (a limping disk)
    "slow_peer",   # egress token-bucket bandwidth cap + CPU-starve duty
                   # cycle `arg` on targets (a rate-limited NIC / a
                   # CPU-starved host) — distinct from `delay`, which
                   # models the LINK in the receiver's messenger thread
                   # and leaves the sender at full speed
    "mem_pressure",  # bounded WAL write-back buffer (`arg` bytes): group
                   # commit degrades to constant forced fsyncs + reclaim
                   # stalls (memory pressure on the durability path)
    "range_change",  # live resharding under fire (host/resharding.py):
                   # drive a key-range split through the manager ctrl
                   # plane WHILE partitions/crashes play — the seal ->
                   # barrier -> adopt cutover's adversarial coverage
                   # (arg selects which canonical runner key moves; the
                   # destination group rides the first target id,
                   # normalized mod G server-side).  Leaderless
                   # protocols answer with an explicit refusal — the
                   # reply path is still exercised, like conf_change
    "proxy_crash",  # serving-plane tier fault (host/ingress.py): kill an
                   # ingress PROXY (targets = proxy indices, not replica
                   # ids) and restart it after `duration` ticks — its
                   # ctrl-connection drop deregisters it at the manager,
                   # so clients must rediscover the tier via the
                   # re-announce in their next query_info/rotate.  Played
                   # through NemesisRunner.proxy_ctl (the soak wires it
                   # to a live ServingPlane); plans without an attached
                   # proxy tier record the action as an error, not fatal
)

# slow_peer host-lowering constants: the bandwidth cap is sized so a
# 3-replica localhost mesh limps (frames stall tens of ms/tick) without
# looking dead — heartbeats still land well inside election timeouts
SLOW_PEER_BW = 48_000.0  # bytes/second egress

# classes with no device-plane lowering: frame-level delay/duplication are
# netmodel *config* (delay line depth), not per-tick masks, the WAL /
# snapshot files are host-only, and the conf plane is driven by host
# inputs the mask compiler does not carry.  compile_device skips these
# (documented weakening).
HOST_ONLY = (
    "delay", "dup", "wal_torn", "wal_fsync", "conf_change",
    "take_snapshot",
    # the resharding ctrl plane is host machinery (manager fan-out +
    # host seal/adopt state); the lockstep device plane has no analog
    "range_change",
    # fail-slow classes are host-only like wal_*: the lockstep device
    # plane has no notion of a replica running SLOWER than the tick (the
    # closest device analog, duty-cycled aliveness, is already
    # clock_skew) — disk latency, egress bandwidth, and allocator
    # pressure live in the host hubs
    "slow_disk", "slow_peer", "mem_pressure",
    # the proxy tier is a host-process tier with no device analog at all
    "proxy_crash",
)
# instantaneous events: no heal action at tick + duration
INSTANT = ("crash", "wal_torn", "wal_fsync", "conf_change",
           "take_snapshot", "range_change")


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One timed fault.  ``tick`` is in nemesis schedule ticks (scaled to
    wall time by the runner, mapped 1:1 to engine ticks by the device
    compiler); a non-instant event holds for ``duration`` ticks and then
    heals."""

    tick: int
    kind: str
    targets: Tuple[int, ...] = ()
    duration: int = 0
    arg: float = 0.0

    def render(self) -> str:
        return (
            f"@{self.tick:05d} {self.kind}"
            f" targets={list(self.targets)}"
            f" dur={self.duration} arg={self.arg:g}"
        )


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    seed: int
    population: int
    ticks: int
    events: Tuple[FaultEvent, ...]

    # ------------------------------------------------------------ build
    @staticmethod
    def generate(
        seed: int,
        population: int,
        ticks: int,
        classes: Sequence[str] = ALL_CLASSES,
        heal_tail: Optional[int] = None,
    ) -> "FaultPlan":
        """Draw a sequential (non-overlapping) schedule from the seed.

        Victim counts are capped at a sub-quorum minority so the cluster
        can keep serving (or at least electing) *during* the fault, and
        every fault heals before ``heal_tail`` — the final fault-free
        stretch the soak's recovery assertion runs in.
        """
        import random

        for c in classes:
            if c not in ALL_CLASSES:
                raise ValueError(f"unknown fault class {c!r}")
        rng = random.Random(seed)
        R = population
        max_victims = max(1, (R - 1) // 2)
        if heal_tail is None:
            heal_tail = max(10, ticks // 4)
        events: List[FaultEvent] = []
        t = rng.randint(2, 6)
        while t < ticks - heal_tail:
            kind = rng.choice(list(classes))
            dur = rng.randint(4, max(5, ticks // 6))
            if t + dur >= ticks - heal_tail:
                dur = ticks - heal_tail - t - 1
                if dur < 2 and kind not in INSTANT:
                    break
            nv = rng.randint(1, max_victims)
            targets = tuple(sorted(rng.sample(range(R), nv)))
            arg = 0.0
            if kind == "one_way":
                src, dst = rng.sample(range(R), 2)
                targets = (src, dst)
            elif kind == "drop":
                arg = round(rng.uniform(0.1, 0.5), 3)
            elif kind == "dup":
                arg = round(rng.uniform(0.1, 0.4), 3)
            elif kind == "delay":
                arg = round(rng.uniform(0.02, 0.2), 3)
            elif kind == "clock_skew":
                # tick-rate scale: 0.3 = the victim's clock runs at 30%
                # of the cluster's (the lease planes are the at-risk
                # consumer — a slow holder's countdowns outlive the
                # grantor's real-time intent)
                arg = round(rng.uniform(0.3, 0.8), 3)
            elif kind == "wal_fsync":
                arg = float(rng.randint(1, 3))
            elif kind == "slow_disk":
                # latency inflation factor: severe enough to tank the
                # victim's tick loop (one group-commit fsync per busy
                # tick) while staying far from fail-stop
                arg = float(rng.randint(10, 30))
            elif kind == "slow_peer":
                # CPU-starve duty cycle; the egress bandwidth cap rides
                # along at SLOW_PEER_BW in the lowering
                arg = round(rng.uniform(0.5, 0.85), 3)
            elif kind == "mem_pressure":
                # write-back buffer cap in BYTES: smaller than one
                # tick's WAL records, so nearly every append forces an
                # inline fsync + reclaim stall
                arg = float(rng.choice((256, 512)))
            elif kind == "take_snapshot":
                # ~1/3 of snapshots crash between the snapshot write and
                # the WAL truncate — the window where a half-finished
                # compaction must still recover losslessly
                arg = 1.0 if rng.random() < 0.34 else 0.0
            elif kind == "range_change":
                # which canonical runner key to split off (the runner's
                # range_keys tuple); targets[0] seeds the destination
                arg = float(rng.randint(0, 2))
            if kind in INSTANT:
                dur = 0
            events.append(FaultEvent(t, kind, targets, dur, arg))
            # crashes are wall-serialized by the manager (ack + rejoin),
            # and a crash-armed snapshot restarts its victims through the
            # supervisor; leave slack so the next event still lands in
            # its window
            gap = rng.randint(3, 9) + (
                6 if kind in ("crash", "device_reset")
                or (kind == "take_snapshot" and arg > 0) else 0
            )
            t += max(dur, 1) + gap
        return FaultPlan(seed, population, ticks, tuple(events))

    @staticmethod
    def failslow(
        kind: str,
        seed: int,
        population: int,
        ticks: int,
        arg: Optional[float] = None,
    ) -> "FaultPlan":
        """Canonical single-event gray-failure plan for the fail-slow
        soak matrix: one ``kind`` event starting a few ticks in and
        holding until a short heal tail — long enough for detection,
        demotion, and a post-mitigation throughput window to play out
        WHILE the victim is still limping.

        The event targets replica 0 as a placeholder; the soak runner
        retargets it to the LIVE leader at fire time (the victim that
        makes fail-slow a group-wide outage), exactly like the workload
        soak's mid-burst leader crash.  The digest covers this canonical
        form, so committed NEMESIS.json fail-slow rows stay replayable
        per seed while the victim stays a runtime decision.
        """
        import random

        if kind not in ("slow_disk", "slow_peer", "mem_pressure"):
            raise ValueError(f"not a fail-slow class: {kind!r}")
        import zlib

        # stable per-class stream: str.__hash__ is process-randomized
        # and would break the byte-identical-per-seed digest contract
        rng = random.Random((seed << 8) ^ (zlib.crc32(kind.encode()) % 251))
        onset = rng.randint(4, 8)
        if arg is None:
            # severities above the generate() ranges: the soak's twin
            # cells must make the victim's tick unambiguously dominated
            # by the limp (the >= 2x mitigated-throughput assertion),
            # while staying far under election timeouts — gray, not dead
            arg = {
                "slow_disk": float(rng.randint(40, 60)),
                "slow_peer": round(rng.uniform(0.7, 0.85), 3),
                # pathological allocator: smaller than ANY WAL record,
                # so every append pays a direct-reclaim flush
                "mem_pressure": float(rng.choice((64, 128))),
            }[kind]
        heal_tail = max(6, ticks // 8)
        dur = max(4, ticks - onset - heal_tail)
        ev = FaultEvent(onset, kind, (0,), dur, float(arg))
        return FaultPlan(seed, population, ticks, (ev,))

    @staticmethod
    def proxy_crash(
        seed: int,
        population: int,
        ticks: int,
        proxies: int = 2,
        at: Optional[int] = None,
        restart_after: int = 10,
    ) -> "FaultPlan":
        """Canonical single-event proxy-tier crash plan: kill ingress
        proxy ``seed % proxies`` at schedule tick ``at`` (or a seeded
        point ~1/3 in) and restart it ``restart_after`` ticks later.
        Targets are PROXY indices; the soak runner plays it against a
        live :class:`~summerset_tpu.host.ingress.ServingPlane` via
        ``NemesisRunner.proxy_ctl``.  Deterministic given its arguments,
        so committed rows regenerate the digest without a cluster —
        the same contract as :meth:`failslow`."""
        import random

        rng = random.Random((seed << 8) ^ 0x9C)
        if at is None:
            at = rng.randint(max(2, ticks // 3), max(3, ticks // 2))
        pidx = seed % max(1, int(proxies))
        ev = FaultEvent(
            int(at), "proxy_crash", (pidx,),
            max(1, int(restart_after)), 0.0,
        )
        return FaultPlan(seed, population, ticks, (ev,))

    # ------------------------------------------------------- determinism
    def timeline(self) -> str:
        """Canonical rendering; byte-identical for identical plans (the
        repro contract — soak failures print this plus the seed)."""
        head = (
            f"# FaultPlan v1 seed={self.seed}"
            f" population={self.population} ticks={self.ticks}\n"
        )
        return head + "".join(e.render() + "\n" for e in self.events)

    def digest(self) -> str:
        return hashlib.sha256(self.timeline().encode()).hexdigest()[:16]

    # ----------------------------------------------------- device plane
    def compile_device(self, G: int) -> Dict[str, Any]:
        """Lower to per-tick ``alive`` [T, G, R] / ``link_up`` [T, G, R, R]
        / ``reset`` [T, G, R] mask sequences for ``Engine.run_ticks``
        (append to its ``inputs_seq``).  Crash lowers to freeze-and-thaw
        (``alive`` down for the duration) — the pause-like legacy model;
        ``device_reset`` is the durable crash: down for the duration,
        then the ``reset`` mask fires on the thaw tick and the engine
        rebuilds the state row from only the kernel's declared durable
        leaves (``engine.reset_durable_rows``), so volatile state is
        demonstrably lost.  ``HOST_ONLY`` classes are skipped here."""
        from ..core.netmodel import ControlInputs

        T, R = self.ticks, self.population
        alive = np.ones((T, G, R), bool)
        link = np.ones((T, G, R, R), bool)
        reset = np.zeros((T, G, R), bool)
        for ev in self.events:
            lo = ev.tick
            hi = min(ev.tick + max(ev.duration, 1), T)
            if lo >= T:
                continue
            if ev.kind in ("crash", "pause", "device_reset"):
                alive[lo:hi][:, :, list(ev.targets)] = False
                if ev.kind == "device_reset" and hi < T:
                    # restart-from-durable-lanes on the thaw tick: the
                    # replica steps tick `hi` already reborn (alive, but
                    # with every volatile leaf zeroed)
                    reset[hi][:, list(ev.targets)] = True
            elif ev.kind == "clock_skew":
                # duty-cycled alive: the victim steps only on ticks where
                # its scaled clock advances a whole tick (deterministic —
                # no RNG — so the compiled masks stay byte-identical).
                # Under lockstep semantics this is the adversarial
                # superset of real skew: countdowns crawl AND off-tick
                # deliveries are lost (see ControlInputs.skew_alive).
                m = np.asarray(ControlInputs.skew_alive(
                    G, R, hi - lo, {t: ev.arg for t in ev.targets},
                    offset=lo,
                ))
                alive[lo:hi] &= m
            elif ev.kind == "partition":
                m = np.asarray(
                    ControlInputs.split_links(G, R, ev.targets)
                )
                link[lo:hi] &= m[None]
            elif ev.kind == "isolate":
                m = np.asarray(
                    ControlInputs.isolate_links(G, R, *ev.targets)
                )
                link[lo:hi] &= m[None]
            elif ev.kind == "one_way":
                src, dst = ev.targets
                m = np.asarray(
                    ControlInputs.one_way_down(G, R, src, dst)
                )
                link[lo:hi] &= m[None]
            elif ev.kind == "drop":
                # iid per-(tick, group, link) loss, seeded off the plan:
                # the same seed compiles the same loss pattern
                rng = np.random.default_rng([self.seed, ev.tick])
                keep = rng.random((hi - lo, G, R, R)) >= ev.arg
                sel = np.zeros(R, bool)
                sel[list(ev.targets)] = True
                keep |= ~sel[None, None, :, None]  # only targets' egress
                keep |= np.eye(R, dtype=bool)[None, None]  # self-links up
                link[lo:hi] &= keep
        return {"alive": alive, "link_up": link, "reset": reset}

    # ------------------------------------------------------- host plane
    def host_actions(self) -> List[Tuple[int, str, str, dict]]:
        """Flatten to a sorted action list for the live-cluster runner:
        ``(tick, action, desc, spec)`` where ``action`` names a runner
        verb and ``spec`` its arguments.  Duration events contribute an
        explicit heal action at ``tick + duration``."""
        acts: List[Tuple[int, str, str, dict]] = []
        R = self.population

        def others(ts):
            return [r for r in range(R) if r not in ts]

        for ev in self.events:
            ts = list(ev.targets)
            end = ev.tick + ev.duration
            if ev.kind in ("crash", "device_reset"):
                # on the host plane BOTH are durable crash-restarts (the
                # live replica already loses its volatile process state);
                # device_reset's distinct lowering is device-side only
                acts.append((ev.tick, "reset", ev.render(),
                             {"servers": ts}))
            elif ev.kind == "pause":
                acts.append((ev.tick, "pause", ev.render(),
                             {"servers": ts}))
                acts.append((end, "resume", f"@{end:05d} resume"
                             f" targets={ts}", {"servers": ts}))
            elif ev.kind in ("partition", "isolate"):
                # cutting both directions at the victims' side alone
                # severs the link: egress dies at their mute, ingress
                # from the far side dies at their deaf
                if ev.kind == "partition":
                    spec = {"mute": others(ts), "deaf": others(ts)}
                    net = {r: spec for r in ts}
                else:
                    net = {
                        r: {
                            "mute": [p for p in range(R) if p != r],
                            "deaf": [p for p in range(R) if p != r],
                        }
                        for r in ts
                    }
                acts.append((ev.tick, "net", ev.render(), {"per": net}))
                acts.append((end, "net_clear", f"@{end:05d} heal"
                             f" targets={ts}", {"servers": ts}))
            elif ev.kind == "one_way":
                src, dst = ev.targets
                acts.append((ev.tick, "net", ev.render(),
                             {"per": {src: {"mute": [dst]}}}))
                acts.append((end, "net_clear", f"@{end:05d} heal"
                             f" targets=[{src}]", {"servers": [src]}))
            elif ev.kind in ("drop", "delay", "dup"):
                key = {"drop": "drop", "delay": "delay", "dup": "dup"}[
                    ev.kind
                ]
                spec = {key: {"*": ev.arg}}
                acts.append((ev.tick, "net", ev.render(),
                             {"per": {r: spec for r in ts}}))
                acts.append((end, "net_clear", f"@{end:05d} heal"
                             f" targets={ts}", {"servers": ts}))
            elif ev.kind == "clock_skew":
                # host lowering: stretch the victims' tick interval by
                # 1/rate through the fault_ctl plane; heal restores 1.0
                acts.append((ev.tick, "skew", ev.render(),
                             {"servers": ts,
                              "factor": round(1.0 / ev.arg, 3)}))
                acts.append((end, "skew", f"@{end:05d} skew heal"
                             f" targets={ts}",
                             {"servers": ts, "factor": None}))
            elif ev.kind == "conf_change":
                # responders := targets — driven through the data plane
                # (a real client ConfChange) while the rest of the
                # schedule keeps playing
                acts.append((ev.tick, "conf_change", ev.render(),
                             {"responders": ts}))
            elif ev.kind == "take_snapshot":
                acts.append((ev.tick, "take_snapshot", ev.render(),
                             {"servers": ts, "crash": bool(ev.arg)}))
            elif ev.kind == "range_change":
                # a live split driven through the ctrl plane while the
                # rest of the schedule keeps playing (normalized mod G
                # at the servers — a G=1 cluster still exercises the
                # full seal/barrier/adopt cutover as a self-move)
                acts.append((ev.tick, "range_change", ev.render(),
                             {"sel": int(ev.arg),
                              "dst": ts[0] if ts else 0}))
            elif ev.kind == "slow_disk":
                acts.append((ev.tick, "wal", ev.render(),
                             {"servers": ts, "spec": {"slow": ev.arg}}))
                acts.append((end, "wal", f"@{end:05d} slow_disk heal"
                             f" targets={ts}",
                             {"servers": ts, "spec": None}))
            elif ev.kind == "mem_pressure":
                acts.append((ev.tick, "wal", ev.render(),
                             {"servers": ts,
                              "spec": {"mem": int(ev.arg)}}))
                acts.append((end, "wal", f"@{end:05d} mem_pressure heal"
                             f" targets={ts}",
                             {"servers": ts, "spec": None}))
            elif ev.kind == "slow_peer":
                spec = {"bw": SLOW_PEER_BW, "starve": ev.arg}
                acts.append((ev.tick, "net", ev.render(),
                             {"per": {r: spec for r in ts}}))
                acts.append((end, "net_clear", f"@{end:05d} slow_peer "
                             f"heal targets={ts}", {"servers": ts}))
            elif ev.kind == "proxy_crash":
                # targets are PROXY indices (the runner's proxy_ctl maps
                # them onto the live ServingPlane); the heal action is
                # the restart — a fresh incarnation on the same port
                acts.append((ev.tick, "proxy_crash", ev.render(),
                             {"proxies": ts}))
                acts.append((end, "proxy_restart",
                             f"@{end:05d} proxy restart targets={ts}",
                             {"proxies": ts}))
            elif ev.kind == "wal_torn":
                acts.append((ev.tick, "wal", ev.render(),
                             {"servers": ts, "spec": {"torn": 1}}))
            elif ev.kind == "wal_fsync":
                acts.append((
                    ev.tick, "wal", ev.render(),
                    {"servers": ts,
                     "spec": {"fsync_fail": int(ev.arg)}},
                ))
        acts.sort(key=lambda a: a[0])
        return acts


class NemesisRunner:
    """Plays a FaultPlan against a live cluster through the manager
    control plane.  One schedule tick maps to ``tick_len`` wall seconds;
    blocking actions (manager-serialized crash-restarts) may slide later
    events' wall times, but never their order or logical ticks — the
    logical timeline IS the plan."""

    def __init__(
        self,
        manager_addr: Tuple[str, int],
        plan: FaultPlan,
        tick_len: float = 0.25,
        on_action: Optional[Callable[[int, str], None]] = None,
    ):
        from ..client.endpoint import GenericEndpoint

        self.plan = plan
        self.tick_len = tick_len
        self.manager_addr = manager_addr
        self.ep = GenericEndpoint(manager_addr)  # ctrl stub only
        self.executed: List[Tuple[int, str]] = []
        self._on_action = on_action
        # serving-plane hook: the soak wires this to a live
        # ServingPlane so proxy_crash/proxy_restart actions land on real
        # proxy processes; plans scheduling proxy faults without a tier
        # attached record the action error (not fatal) like any other
        # impossible fault action
        self.proxy_ctl: Optional[Callable[[str, dict], None]] = None
        # canonical keys range_change events move (sel = arg indexes
        # this tuple); soaks override it with keys their workload
        # actually writes so the cutover carries real state
        self.range_keys: Tuple[str, ...] = ("nem0", "nem1", "nem2")
        # in-flight conf_change driver threads: conf entries ride the log
        # and may take many ticks to install under faults — the schedule
        # must keep playing WHILE they do (that concurrency is the point)
        self._conf_threads: List[threading.Thread] = []

    # --------------------------------------------------------- plumbing
    def _request(self, req: CtrlRequest, timeout: float = 60.0):
        return self.ep.ctrl.request(req, timeout=timeout)

    def _inject(self, servers: List[int], payload: dict) -> None:
        payload = dict(payload)
        payload.setdefault(
            "seed", self.plan.seed * 1000003 % (1 << 31)
        )
        self._request(CtrlRequest(
            "inject_faults", servers=servers, payload=payload,
        ))

    def _run_action(self, action: str, spec: dict) -> None:
        if action == "reset":
            # durable crash-restart; serialized by the manager (ack,
            # id free, rejoin) — the long pole of the schedule
            self._request(
                CtrlRequest("reset_servers", servers=spec["servers"],
                            durable=True),
                timeout=240.0,
            )
        elif action == "pause":
            self._request(CtrlRequest(
                "pause_servers", servers=spec["servers"]))
        elif action == "resume":
            self._request(CtrlRequest(
                "resume_servers", servers=spec["servers"]))
        elif action == "net":
            for sid, net in spec["per"].items():
                self._inject([sid], {"net": net})
        elif action == "net_clear":
            self._inject(spec["servers"], {"net": None})
        elif action == "wal":
            self._inject(spec["servers"], {"wal": spec["spec"]})
        elif action == "skew":
            self._inject(spec["servers"], {"skew": spec["factor"]})
        elif action == "conf_change":
            self._start_conf_change(list(spec["responders"]))
        elif action == "range_change":
            self._start_range_change(int(spec["sel"]), int(spec["dst"]))
        elif action in ("proxy_crash", "proxy_restart"):
            if self.proxy_ctl is None:
                raise SummersetError(
                    "proxy fault scheduled but no serving plane attached"
                )
            self.proxy_ctl(action, spec)
        elif action == "take_snapshot":
            if spec.get("crash"):
                # arm the crash point FIRST: the snapshot request then
                # dies between the snapshot write and the WAL truncate,
                # and the victim's supervisor restart exercises the
                # new-snapshot + old-WAL recovery path
                self._inject(spec["servers"], {"snap_crash": 1})
            self._request(
                CtrlRequest("take_snapshot", servers=spec["servers"]),
                timeout=60.0,
            )

    def _start_conf_change(self, responders: List[int]) -> None:
        """Fire a real client ConfChange from a background driver; the
        schedule does NOT wait for installation — partitions/crashes
        keep playing against the in-flight revoke-then-adopt barrier."""
        from ..client.drivers import DriverClosedLoop
        from ..client.endpoint import GenericEndpoint

        def drive() -> None:
            ep = None
            try:
                ep = GenericEndpoint(self.manager_addr)
                ep.connect()
                drv = DriverClosedLoop(ep, timeout=8.0)
                drv.conf_change({"responders": responders}, retries=4)
            except Exception as e:
                # expected under adversity: a conf-less protocol answers
                # failure fast, a partitioned cluster may time the driver
                # out — the attempt itself is the coverage
                pf_warn(logger, f"conf_change {responders} gave up: {e}")
            finally:
                if ep is not None:
                    try:
                        ep.leave()
                    except Exception:
                        pass

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        self._conf_threads.append(t)

    def _start_range_change(self, sel: int, dst: int) -> None:
        """Fire a live range split through the manager ctrl plane from a
        background driver; the schedule keeps playing WHILE the seal ->
        barrier -> adopt cutover is in flight (that concurrency is the
        coverage).  The manager normalizes dst mod G, so any seeded
        target id is a valid destination group."""
        from ..host.resharding import single_key_range

        key = self.range_keys[sel % len(self.range_keys)]
        start, end = single_key_range(key)

        def drive() -> None:
            try:
                self._request(CtrlRequest("range_change", payload={
                    "op": "split", "start": start, "end": end,
                    "dst_group": int(dst),
                }), timeout=60.0)
            except Exception as e:
                # expected under adversity: a partitioned manager fan-
                # out may time out — the attempt itself is the coverage
                pf_warn(logger, f"range_change {key!r} gave up: {e}")

        t = threading.Thread(target=drive, daemon=True)
        t.start()
        self._conf_threads.append(t)

    # ------------------------------------------------------------- play
    def play(self, stop: Optional[threading.Event] = None) -> None:
        """Execute the schedule; returns after the last action (all
        durations healed).  ``stop`` aborts between actions."""
        t0 = time.monotonic()
        for tick, action, desc, spec in self.plan.host_actions():
            if stop is not None and stop.is_set():
                break
            lag = t0 + tick * self.tick_len - time.monotonic()
            if lag > 0:
                time.sleep(lag)
            try:
                self._run_action(action, spec)
                self.executed.append((tick, desc))
                pf_info(logger, f"nemesis {desc}")
            except Exception as e:
                # a fault action failing (e.g. victim already down) is
                # recorded, not fatal — the heal pass below re-clears
                self.executed.append((tick, f"{desc} !error {e}"))
                pf_warn(logger, f"nemesis action failed: {desc}: {e}")
            if self._on_action is not None:
                self._on_action(tick, desc)
        # drain in-flight conf drivers (bounded: their own retry budgets
        # already cap them) so a late install never races teardown
        for t in self._conf_threads:
            t.join(timeout=60.0)

    def flight_tails(self, last_n: int = 256) -> Dict[str, Any]:
        """Per-replica flight-recorder tails (graftscope) for failure
        repro bundles: what each survivor was doing in its final ticks,
        alongside the seed + history the bundle already carries.  Must
        run while the cluster is still up (the soak calls it before
        teardown); best-effort — diagnostics never mask the verdict."""
        try:
            rep = self._request(CtrlRequest(
                "flight_dump", payload={"last_n": int(last_n)},
            ), timeout=30.0)
            return {
                str(sid): dump
                for sid, dump in sorted((rep.payloads or {}).items())
            }
        except Exception as e:
            pf_warn(logger, f"flight scrape failed: {e}")
            return {}

    def heal_all(self) -> None:
        """Belt-and-braces final heal: clear every injector and resume
        everyone, so the recovery assertion never races a leftover
        fault."""
        try:
            self._inject(
                list(range(self.plan.population)),
                {"net": None, "wal": None, "skew": None,
                 "snap_crash": None},
            )
        except Exception:
            pass
        try:
            self._request(CtrlRequest("resume_servers", servers=None))
        except Exception:
            pass

    def close(self) -> None:
        try:
            self.ep.leave()
        except Exception:
            pass
