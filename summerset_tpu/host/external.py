"""ExternalApi: the client-facing TCP plane with request batching.

Parity: reference ``src/server/external.rs`` — an acceptor task spawning a
servant task per client, plus a **batch ticker**: requests accumulate in a
queue that the replica drains every ``batch_interval`` seconds (capped at
``max_batch_size``), matching the reference's Notify-based ticker dump
(external.rs:697-730).  Clients identify themselves by sending their
assigned id as the first frame.  Replies are routed back through the
servant owning that client's connection.

Ingress backpressure (the overload-survival contract the workload plane
soaks): the pending queue is BOUNDED at ``max_pending``.  A data-plane
request arriving at a full queue is refused on the spot with an
``ApiReply(kind="shed", retry_after_ms=...)`` — an explicit negative
ack sent before the request ever enters the queue, so a shed op is
GUARANTEED never proposed (``utils/linearize`` excludes shed puts on
exactly that guarantee).  The retry-after hint is the queue's estimated
drain time (depth over an EWMA of the replica's observed batch-take
rate), so backed-off clients return roughly when space exists instead
of synchronously hammering a still-full queue.  Sheds are never silent:
the ``api_shed`` counter, the ``api_queue_depth`` gauge, and a typed
``api_shed`` flight-recorder event make every refusal attributable in
telemetry and on graftscope request chains.  Conf/leave requests bypass
the bound (control-plane ops are rare and must not starve under data
overload).
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils import safetcp, wirecodec
from ..utils.logging import pf_debug, pf_info, pf_logger
from .messages import ApiReply, ApiRequest

logger = pf_logger("external")


class ExternalApi:
    """The reusable client-facing ingress tier.

    Composability (the serving-plane split, ``host/ingress.py``): this
    class is the front door of BOTH tiers — a fused/shard server runs it
    with the default ``metric_ns="api"``, an ingress proxy embeds its own
    instance under ``metric_ns="proxy"`` so the same counters surface as
    the proxy-tier series (``proxy_shed`` / ``proxy_queue_depth`` / ...)
    and per-tier shed attribution falls out of the namespace alone.

    Queue accounting for the bounded ingress: one ``"req"``, ``"probe"``,
    or ``"batch"`` request is ONE pending slot regardless of how many
    commands a batch aggregates — that slot-per-batch rule is exactly the
    fan-in amortization that lets a proxy tier raise the shard's shed
    point (the shard drains ``max_batch_size`` *entries* per tick, each
    carrying a whole proxy batch).  A shed refusal for a batch covers the
    whole batch with one negative ack; ``conf``/``sub``/``leave`` bypass
    the bound (rare control-plane ops must not starve under data
    overload — a subscription is one request per learner lifetime).
    """

    #: request kinds subject to the bounded-queue shed rule ("scan" is
    #: the ordered range read — data plane, so it pays the bound too)
    BOUNDED_KINDS = ("req", "batch", "probe", "scan")

    def __init__(
        self,
        api_addr: Tuple[str, int],
        batch_interval: float = 0.001,
        max_batch_size: int = 5000,
        max_pending: int = 16384,
        registry=None,
        flight=None,
        metric_ns: str = "api",
        codec: Optional[bool] = None,
    ):
        self.api_addr = api_addr
        self.batch_interval = batch_interval
        self.max_batch_size = max_batch_size
        # wire codec (utils/wirecodec.py): hot replies (reply/shed/note/
        # probe) leave in the compact binary form; cold kinds and the
        # whole ingress side dispatch per frame, so clients of either
        # persuasion interoperate.  None = process default.
        self.codec = (
            wirecodec.default_on() if codec is None else bool(codec)
        )
        self._enc = wirecodec.FrameEncoder()  # event-loop-thread owned
        # ingress bound: data-plane requests beyond this queue depth are
        # shed with a retry-after hint instead of buffered unboundedly
        self.max_pending = max(1, int(max_pending))
        # metric namespace: "api" on shard servers, "proxy" on the
        # ingress-proxy tier — same seams, per-tier series
        self.metric_ns = str(metric_ns)
        ns = self.metric_ns
        self._m_requests = f"{ns}_requests_total"
        self._m_replies = f"{ns}_replies_total"
        self._m_latency = f"{ns}_request_latency_us"
        self._m_shed = f"{ns}_shed"
        self._m_depth = f"{ns}_queue_depth"
        self._m_evicted = f"{ns}_stamps_evicted"
        # EWMA of the replica's batch-take rate (reqs/s), written by
        # get_req_batch on the replica thread and read (one float load)
        # by servants computing retry-after hints
        self._drain_rate = 0.0
        self._drain_t: Optional[float] = None
        # graftscope seam (host/tracing.FlightRecorder): api_ingress /
        # api_reply events keyed by (client, req_id) — the request-span
        # endpoints the trace exporter joins to the propose/commit chain
        self.flight = flight
        # telemetry seam (host/telemetry.MetricsRegistry): request→reply
        # latency is measured HERE, at the client-facing socket plane —
        # it covers queueing, consensus, durability, and reply routing,
        # the server-side mirror of what clients see.  Arrival stamps are
        # bounded: a request that never draws a reply (redirect storms
        # aside, a crash) ages out instead of leaking.
        self.registry = registry
        if registry is not None:
            # pre-register so the eviction blind spot is visible (and
            # zero) in every snapshot, not only after an overload
            registry.counter_add(self._m_evicted, 0)
            # likewise the backpressure lanes: a zero shed series
            # distinguishes "never overloaded" from "not measured"
            registry.counter_add(self._m_shed, 0)
            registry.gauge_set(self._m_depth, 0)
        self._arrivals: Dict[Tuple[int, int], float] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._writers: Dict[int, asyncio.StreamWriter] = {}
        self._pending: List[Tuple[int, ApiRequest]] = []
        self._batch_ready = threading.Event()
        self._lock = threading.Lock()
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        self._started.wait(timeout=10)

    # -- hub API (called from the replica thread) ---------------------------
    def get_req_batch(
        self, timeout: Optional[float] = None
    ) -> List[Tuple[int, ApiRequest]]:
        """Blocking batch take (parity: ``get_req_batch``,
        external.rs:323-345): waits for the ticker, returns <= max_batch
        requests (possibly empty on timeout)."""
        if not self._batch_ready.wait(timeout=timeout):
            return []
        with self._lock:
            batch = self._pending[: self.max_batch_size]
            del self._pending[: len(batch)]
            depth = len(self._pending)
            if not self._pending:
                self._batch_ready.clear()
        if batch:
            # EWMA drain rate: what the replica actually takes per
            # second (max_batch_size per tick, not per batch_interval —
            # the replica polls once per tick).  Retry-after hints are
            # depth / this rate: roughly when the queue will have space.
            now = time.monotonic()
            t0 = self._drain_t
            if t0 is not None and now > t0:
                inst = len(batch) / (now - t0)
                self._drain_rate = (
                    inst if self._drain_rate <= 0.0
                    else 0.8 * self._drain_rate + 0.2 * inst
                )
            self._drain_t = now
            if self.registry is not None:
                self.registry.gauge_set(self._m_depth, depth)
        return batch

    def has_client(self, client: int) -> bool:
        """Is ``client``'s connection still owned by a servant?  (Dict
        membership read, safe cross-thread: the server's commit-feed
        flush uses it to GC subscribers whose learner connection died.)"""
        return int(client) in self._writers

    def _retry_after_ms(self, depth: int) -> int:
        """Shed hint: estimated ms until the queue has drained ``depth``
        entries, clamped to [5, 1000] (a cold/stalled drain rate must
        not produce an unbounded or zero hint)."""
        rate = self._drain_rate
        if rate <= 0.0:
            return 50
        return int(min(1000.0, max(5.0, 1000.0 * depth / rate)))

    def send_reply(self, reply: ApiReply, client: int) -> None:
        """Route a reply to the servant owning `client`'s connection."""
        loop = self._loop
        if loop is None:
            return
        asyncio.run_coroutine_threadsafe(
            self._send(client, reply), loop
        )

    def send_replies(
        self,
        items: List[Tuple[int, ApiReply]],
        fence=None,
    ) -> None:
        """Flush a batch of ``(client, reply)`` pairs, gated on the
        durability fence: ``fence`` (the pipelined loop's
        ``ServerReplica._fence_wait``) runs BEFORE the first reply is
        handed to the event loop — replies reveal applied/acked state,
        so none may escape until the WAL records covering that state
        are fsynced, and a failed fence raises here with every reply
        still unsent (the crash-before-ack contract)."""
        if fence is not None:
            fence()
        for client, reply in items:
            self.send_reply(reply, client)

    def stop(self) -> None:
        loop = self._loop
        if loop is not None:
            def _teardown() -> None:
                # close the listener + client conns inside the loop so the
                # api port is actually released (an in-process restart
                # rebinds it immediately); servant sockets get an abortive
                # close — a graceful FIN would park them in FIN_WAIT_2
                # holding the api port while the client end stays open
                if self._server is not None:
                    self._server.close()
                for w in list(self._writers.values()):
                    try:
                        sock = w.get_extra_info("socket")
                        if sock is not None:
                            from .transport import hard_close

                            hard_close(sock)
                        else:
                            w.close()
                    # graftlint: disable=H106 -- best-effort teardown: a servant socket already torn down by its client is the expected race here, and stop() must still close the rest and release the port
                    except Exception:
                        pass
                loop.stop()

            try:
                loop.call_soon_threadsafe(_teardown)
            except RuntimeError:
                pass
        self._thread.join(timeout=5)

    # -- event loop side -----------------------------------------------------
    async def _wire_send(self, writer, reply: ApiReply) -> None:
        """The one egress seam: codec-aware encode (hot kinds only)
        through this instance's own encoder (every caller is a
        coroutine on the one event loop — no lock needed, unlike the
        shared module encoder), with the per-tier ``wire_encode_us``
        stamp."""
        t0 = time.monotonic()
        buf = safetcp.encode_frame_bytes(reply, self._enc,
                                         codec=self.codec)
        if self.registry is not None:
            self.registry.observe_s(
                "wire_encode_us", time.monotonic() - t0,
                plane=self.metric_ns,
            )
        writer.write(buf)
        await writer.drain()

    async def _send(self, client: int, reply: ApiReply) -> None:
        reg = self.registry
        if reg is not None:
            t0 = self._arrivals.pop((client, reply.req_id), None)
            if t0 is not None and reply.kind in ("reply", "conf"):
                reg.observe_s(self._m_latency,
                              time.monotonic() - t0)
            reg.counter_add(self._m_replies, kind=reply.kind)
        if self.flight is not None:
            self.flight.record(
                "api_reply", client=client, req_id=reply.req_id,
                kind=reply.kind,
            )
        w = self._writers.get(client)
        if w is None or w.is_closing():
            self._writers.pop(client, None)
            return
        try:
            await self._wire_send(w, reply)
        except (ConnectionError, asyncio.IncompleteReadError):
            self._writers.pop(client, None)

    async def _servant(self, reader, writer) -> None:
        """Per-client servant task (parity: external.rs:500+)."""
        try:
            client = await safetcp.recv_msg(reader)  # first frame: client id
        except (asyncio.IncompleteReadError, ConnectionError):
            writer.close()
            return
        self._writers[int(client)] = writer
        pf_debug(logger, f"accepted client {client}")
        reg = self.registry
        try:
            while True:
                req, t_dec = await safetcp.recv_msg_timed(reader)
                if reg is not None:
                    reg.observe_s(
                        "wire_decode_us", t_dec, plane=self.metric_ns
                    )
                if not isinstance(req, ApiRequest):
                    continue
                if req.kind == "leave":
                    await safetcp.send_msg(
                        writer, ApiReply(kind="leave", req_id=req.req_id)
                    )
                    break
                if req.kind in self.BOUNDED_KINDS:
                    # bounded ingress (conf/sub/leave bypass the bound —
                    # rare control ops must not starve under data
                    # overload; a proxy batch is ONE slot, and its shed
                    # refusal below covers the whole batch with one
                    # negative ack).  The check-then-append split below is
                    # still race-free against other servants: they are
                    # coroutines on THIS loop and nothing between the
                    # check and the append awaits, while the replica
                    # thread only ever SHRINKS the queue — so the depth
                    # read here can only overestimate, never undershoot,
                    # and the bound holds strictly.  Stamping ingress
                    # before the append (not after) keeps the
                    # flight-recorder ordering invariant: a request's
                    # api_ingress always precedes any propose that
                    # consumed it.
                    with self._lock:
                        depth = len(self._pending)
                    if depth >= self.max_pending:
                        hint = self._retry_after_ms(depth)
                        if self.registry is not None:
                            self.registry.counter_add(
                                self._m_requests
                            )
                            self.registry.counter_add(self._m_shed)
                            # the shed IS this request's reply; keep
                            # the requests/replies counter pair
                            # reconcilable under sustained overload
                            self.registry.counter_add(
                                self._m_replies, kind="shed"
                            )
                        if self.flight is not None:
                            self.flight.record(
                                "api_shed", client=int(client),
                                req_id=req.req_id, retry_ms=hint,
                                depth=depth,
                            )
                        await self._wire_send(writer, ApiReply(
                            kind="shed", req_id=req.req_id,
                            success=False, retry_after_ms=hint,
                        ))
                        continue
                if self.flight is not None:
                    self.flight.record(
                        "api_ingress", client=int(client),
                        req_id=req.req_id, kind=req.kind,
                    )
                if self.registry is not None:
                    self.registry.counter_add(self._m_requests)
                if self.registry is not None and req.kind != "batch":
                    # only kinds whose reply echoes this req_id are
                    # stamped: a proxy batch is answered PER-PRID, so
                    # its bid stamp would never be popped — thousands
                    # of phantom entries would evict live stamps and
                    # skew the latency histogram optimistic
                    arr = self._arrivals
                    arr[(int(client), req.req_id)] = time.monotonic()
                    if len(arr) > 8192:  # age out reply-less stamps
                        # the oldest stamps are exactly the slowest
                        # outstanding requests, so their loss skews
                        # the latency histogram optimistic — count the
                        # evictions so the gap is diagnosable
                        for k in list(arr)[:4096]:
                            del arr[k]
                        self.registry.counter_add(
                            self._m_evicted, 4096
                        )
                with self._lock:
                    self._pending.append((int(client), req))
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            self._writers.pop(int(client), None)
            try:
                writer.close()
            except RuntimeError:
                pass  # loop already closed during teardown

    async def _ticker(self) -> None:
        """Batch ticker (parity: external.rs:697-730)."""
        while True:
            await asyncio.sleep(self.batch_interval)
            with self._lock:
                if self._pending:
                    self._batch_ready.set()

    async def _main(self) -> None:
        host, port = self.api_addr
        self._server = await safetcp.tcp_bind_with_retry(
            host, port, self._servant
        )
        self._ticker_task = asyncio.ensure_future(self._ticker())
        # readiness log line is a de-facto API parsed by cluster scripts
        # (reference: workflow_test.py:57-68)
        pf_info(logger, f"accepting clients @ {host}:{port}")
        self._started.set()

    def _run(self) -> None:
        loop = asyncio.new_event_loop()
        self._loop = loop
        asyncio.set_event_loop(loop)
        loop.run_until_complete(self._main())
        try:
            loop.run_forever()
        finally:
            from ..utils.loops import drain_and_close

            drain_and_close(loop)
