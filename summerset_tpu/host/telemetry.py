"""Host metrics registry: counters, gauges, exponential-bucket histograms.

The host half of the telemetry plane (the device half is
``core/telemetry.py``'s in-kernel metric lanes).  One ``MetricsRegistry``
per server process, threaded through every hub seam:

- ``ExternalApi``   — request→reply latency, request/reply counts;
- ``TransportHub``  — frames/bytes per peer (both directions), reconnects;
- ``StorageHub``    — fsync latency, group-commit batch size, appends;
- ``ServerReplica`` — run-loop stage breakdown (intake/exchange/step/log/
  apply — the one timing system; the old ad-hoc ``record_breakdown``
  stopwatch dict is gone), payload-plane egress gauges, and sampled
  per-request slot traces whose ticks-to-commit distribution finally
  measures the host-plane latency story server-side.

Everything is pull-based: hub writes are lock-guarded increments; the
``metrics_dump`` ctrl scrape (``host/server.py`` ``metrics_snapshot``)
serializes one deterministic, JSON-able snapshot.

Histogram shape: power-of-two buckets over non-negative integer samples
(microseconds for latencies, counts for sizes): bucket ``i`` holds
samples with ``bit_length == i`` (0 goes to bucket 0), i.e. bounds
1, 2, 4, ... — 64 buckets cover anything an int64 can hold.  Snapshots
emit buckets sparsely ({index: count}) plus count/sum/min/max and
bucket-interpolated p50/p99, so committed artifacts stay small.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

_NB = 64


def _key(name: str, labels: Dict[str, Any]) -> str:
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Histogram:
    """Exponential (power-of-two) bucket histogram over integer samples."""

    __slots__ = ("count", "total", "vmin", "vmax", "buckets")

    def __init__(self):
        self.count = 0
        self.total = 0
        self.vmin: Optional[int] = None
        self.vmax = 0
        self.buckets = [0] * _NB

    def observe(self, value) -> None:
        v = max(0, int(value))
        self.count += 1
        self.total += v
        self.vmin = v if self.vmin is None else min(self.vmin, v)
        self.vmax = max(self.vmax, v)
        self.buckets[min(v.bit_length(), _NB - 1)] += 1

    def quantile(self, q: float) -> float:
        """Bucket-interpolated quantile (0 <= q <= 1), clamped to the
        observed [min, max] (interpolation inside the top bucket would
        otherwise overshoot the largest sample actually seen)."""
        if self.count == 0:
            return 0.0
        rank = q * (self.count - 1)
        seen = 0
        for i, n in enumerate(self.buckets):
            if n == 0:
                continue
            if seen + n > rank:
                lo = 0 if i == 0 else 1 << (i - 1)
                hi = (1 << i) - 1
                frac = (rank - seen) / n
                v = lo + frac * (hi - lo)
                return min(max(v, float(self.vmin or 0)), float(self.vmax))
            seen += n
        return float(self.vmax)

    def since(self, prev: Optional["Histogram"]) -> "Histogram":
        """Windowed view: a histogram of only the samples recorded after
        ``prev`` was captured (for periodic prints that must reflect
        RECENT behavior — lifetime-cumulative quantiles pin to history
        and hide a fresh regression).  min/max are not delta-decodable
        from counts, so the window inherits the cumulative ones."""
        if prev is None:
            return self
        out = Histogram()
        out.count = self.count - prev.count
        out.total = self.total - prev.total
        out.vmin = self.vmin
        out.vmax = self.vmax
        out.buckets = [
            a - b for a, b in zip(self.buckets, prev.buckets)
        ]
        return out

    def copy(self) -> "Histogram":
        out = Histogram()
        out.count = self.count
        out.total = self.total
        out.vmin = self.vmin
        out.vmax = self.vmax
        out.buckets = list(self.buckets)
        return out

    def merge(self, other: Optional["Histogram"]) -> "Histogram":
        """Fold ``other``'s samples into this histogram in place (and
        return self).  The inverse of :meth:`since`:
        ``prev.copy().merge(cur.since(prev))`` reproduces ``cur``'s
        count/sum/buckets exactly — the delta-snapshot round-trip the
        graftwatch stream relies on.  min/max combine conservatively
        (a window inherits cumulative extremes, so merging a window
        back never widens them wrongly)."""
        if other is None or other.count == 0 and other.total == 0 and \
                not any(other.buckets):
            return self
        self.count += other.count
        self.total += other.total
        if other.vmin is not None:
            self.vmin = other.vmin if self.vmin is None \
                else min(self.vmin, other.vmin)
        self.vmax = max(self.vmax, other.vmax)
        for i, n in enumerate(other.buckets):
            if n:
                self.buckets[i] += n
        return self

    def frac_over(self, threshold: int) -> float:
        """Fraction of samples strictly above ``threshold`` — the
        "error rate" an SLO burn computes from a latency window
        (budget = 1 - objective quantile).  Interpolates inside the
        bucket the threshold lands in, consistent with
        :meth:`quantile`'s uniform-within-bucket model."""
        if self.count <= 0:
            return 0.0
        t = max(0, int(threshold))
        over = 0.0
        for i, n in enumerate(self.buckets):
            if n == 0:
                continue
            lo = 0 if i == 0 else 1 << (i - 1)
            hi = (1 << i) - 1
            if t < lo:
                over += n
            elif t < hi:
                over += n * (hi - t) / (hi - lo)
        return min(1.0, max(0.0, over / self.count))

    @classmethod
    def from_snapshot(cls, snap: Dict[str, Any]) -> "Histogram":
        """Rebuild a histogram from :meth:`snapshot`'s sparse form (the
        shape that rides wire frames and committed artifacts).  JSON
        round-trips stringify bucket indices, so keys may be str."""
        out = cls()
        out.count = int(snap.get("count", 0))
        out.total = int(snap.get("sum", 0))
        out.vmin = None if out.count == 0 else int(snap.get("min", 0))
        out.vmax = int(snap.get("max", 0))
        for i, n in (snap.get("buckets") or {}).items():
            out.buckets[int(i)] = int(n)
        return out

    def snapshot(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "sum": self.total,
            "min": self.vmin or 0,
            "max": self.vmax,
            "p50": round(self.quantile(0.50), 1),
            "p99": round(self.quantile(0.99), 1),
            "buckets": {
                i: n for i, n in enumerate(self.buckets) if n
            },
        }


class MetricsRegistry:
    """Thread-safe named metrics; snapshot order is deterministic."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}

    # -- write side (hub seams) ---------------------------------------------
    def counter_add(self, name: str, inc: int = 1, **labels) -> None:
        k = _key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0) + int(inc)

    def gauge_set(self, name: str, value, **labels) -> None:
        with self._lock:
            self._gauges[_key(name, labels)] = value

    def observe(self, name: str, value, **labels) -> None:
        """Record one histogram sample (integer units: us / bytes / n)."""
        k = _key(name, labels)
        with self._lock:
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = Histogram()
            h.observe(value)

    def observe_s(self, name: str, seconds: float, **labels) -> None:
        """Record a latency sample given in seconds (stored as us)."""
        self.observe(name, int(seconds * 1e6), **labels)

    # -- read side -----------------------------------------------------------
    def hist(self, name: str, **labels) -> Optional[Histogram]:
        return self._hists.get(_key(name, labels))

    def counter_value(self, name: str, **labels) -> int:
        return self._counters.get(_key(name, labels), 0)

    def gauge_value(self, name: str, default: float = 0.0, **labels):
        with self._lock:
            return self._gauges.get(_key(name, labels), default)

    def names(self) -> set:
        """Base metric names present (label suffixes stripped)."""
        with self._lock:
            keys = (
                list(self._counters) + list(self._gauges) + list(self._hists)
            )
        return {k.split("{", 1)[0] for k in keys}

    def export_raw(self) -> Tuple[Dict[str, int], Dict[str, float],
                                  Dict[str, Histogram]]:
        """One consistent point-in-time copy of every series (counters,
        gauges, histogram COPIES) under a single lock hold — the
        graftwatch emitter diffs two of these to build a delta frame.
        Copies are cheap (a histogram is 64 ints); the caller owns them."""
        with self._lock:
            return (
                dict(self._counters),
                dict(self._gauges),
                {k: h.copy() for k, h in self._hists.items()},
            )

    def snapshot(self) -> Dict[str, Any]:
        """Deterministic JSON-able dump: same recorded ops -> same dict."""
        with self._lock:
            return {
                "counters": {
                    k: self._counters[k] for k in sorted(self._counters)
                },
                "gauges": {
                    k: self._gauges[k] for k in sorted(self._gauges)
                },
                "histograms": {
                    k: self._hists[k].snapshot()
                    for k in sorted(self._hists)
                },
            }


class SlotTraces:
    """Sampled per-request slot spans: arrival → proposed tick →
    committed tick → applied tick → replied, for the host serving path.

    ``sample_every = n`` traces every n-th proposed batch per group (1 =
    everything, 0 = off).  Completed traces feed the ``ticks_to_commit``
    and ``ticks_to_apply`` histograms in the registry — the distribution
    behind the host-plane latency cliff that client-side percentiles
    could only hint at — and the last few full traces ride the scrape for
    eyeballing.

    Span building (graftscope): each sampled trace is keyed by its
    ``(g, vid)`` slot identity and carries the representative
    ``(client, req_id)`` of its batch — the junction that connects the
    api-plane ingress/reply events to the slot's propose/commit/apply
    events at export time.  When a ``flight``
    recorder is attached, ``maybe_start`` logs the ``propose`` event
    with both identities so ``scripts/trace_export.py`` can stitch the
    full chain api-arrival → propose → commit → apply → reply.

    Locking: EVERY ``_open`` access holds ``_lock`` — ``maybe_start``
    can ``clear()`` the map under the lock while the mark_* paths run on
    the replica thread, so a lock-free ``get`` could double-observe a
    histogram sample or mutate a dict that was already evicted.
    """

    KEEP = 32

    def __init__(self, registry: MetricsRegistry, sample_every: int = 8,
                 flight=None):
        self.registry = registry
        self.sample_every = max(0, int(sample_every))
        self.flight = flight  # optional tracing.FlightRecorder
        self._n = 0
        self._open: Dict[Tuple[int, int], Dict[str, Any]] = {}
        self._done: list = []
        self._lock = threading.Lock()

    def maybe_start(self, g: int, vid: int, tick: int,
                    arrival_s: float, client: Optional[int] = None,
                    req_id: Optional[int] = None) -> None:
        if self.sample_every == 0:
            return
        with self._lock:
            self._n += 1
            if self._n % self.sample_every:
                return
            if len(self._open) >= 512:  # lost traces must not accumulate
                self._open.clear()
            self._open[(g, vid)] = {
                "g": g, "vid": vid, "t_arrival_s": arrival_s,
                "tick_proposed": tick,
                "client": client, "req_id": req_id,
            }
        if self.flight is not None:
            self.flight.record(
                "propose", g=g, vid=vid, tick=tick,
                client=client, req_id=req_id,
            )

    def mark_committed(self, g: int, vid: int, tick: int) -> None:
        with self._lock:
            tr = self._open.get((g, vid))
            if tr is None or "tick_committed" in tr:
                return
            tr["tick_committed"] = tick
            delta = tick - tr["tick_proposed"]
        self.registry.observe("ticks_to_commit", delta)

    def mark_applied(self, g: int, vid: int, tick: int) -> None:
        with self._lock:
            tr = self._open.get((g, vid))
            if tr is None or "tick_applied" in tr:
                return
            tr["tick_applied"] = tick
            delta = tick - tr["tick_proposed"]
        self.registry.observe("ticks_to_apply", delta)

    def mark_replied(self, g: int, vid: int, now_s: float) -> None:
        with self._lock:
            tr = self._open.pop((g, vid), None)
            if tr is None:
                return
            tr["latency_ms"] = round(
                (now_s - tr.pop("t_arrival_s")) * 1e3, 3
            )
            self._done.append(tr)
            del self._done[: -self.KEEP]

    def sampled(self) -> list:
        with self._lock:
            return list(self._done)


# canonical metric names every live server must expose once it has
# served traffic — the tier-2d smoke gate fails if one goes missing
# (renames must update this tuple AND the README Telemetry table)
DECLARED = (
    "api_request_latency_us",
    "api_requests_total",
    "api_replies_total",
    "api_stamps_evicted",
    # ingress backpressure (host/external.py bounded queue): sheds are
    # pre-registered at zero so "no overload yet" is visible as 0, not
    # as a missing series; queue depth is the gauge the shed decision
    # reads, sampled at every batch take
    "api_shed",
    "api_queue_depth",
    "transport_frames_sent",
    "transport_bytes_sent",
    "transport_frames_recv",
    "transport_bytes_recv",
    "transport_connects",
    # wire-plane codec (utils/wirecodec.py): per-frame serialize /
    # deserialize cost histograms labeled by plane (p2p = tick mesh,
    # api/proxy = client planes) — the A/B row's gated us/op source —
    # plus the SAMPLED bytes-saved counter (every Nth codec frame is
    # also pickled to measure the delta; pre-registered at zero so
    # codec-off runs read as 0, not missing).  wire_codec_on is the
    # mode gauge artifacts stamp.
    "wire_encode_us",
    "wire_decode_us",
    "wire_bytes_saved",
    "wire_codec_on",
    # gray-failure plane (host/health.py): per-peer frame-delivery
    # latency histograms (the slow_peer signal), the replica's own
    # health verdict gauge (1.0 healthy .. 0.0 indicted), and the
    # demotion counter — pre-registered so "never limped" reads as
    # healthy values, not missing series
    "peer_ack_delay_us",
    "health_score",
    "leader_demotions",
    "wal_fsync_us",
    "wal_group_commit_batch",
    "wal_appends_total",
    "loop_stage_us",
    "ticks_to_commit",
    "commits_applied_total",
    "pp_bytes",
    "pp_items",
    # live resharding (host/resharding.py): per-key-range heat at the
    # api seam, executed split/merge cutovers, and seal->adopt latency
    "range_heat",
    "reshard_splits",
    "reshard_merges",
    "reshard_cutover_us",
    # seal-TTL escape hatch (host/server.py _range_unseal): sealed
    # ranges whose destination stayed leaderless past seal_ttl_ticks
    # and rolled back to serving from the source
    "reshard_seal_expired",
    # ordered range reads (scan plane): scans served from applied state
    # (fused lease path or commit-bar barrier), scans refused (sealed
    # span / expired barrier), and total keys returned — pre-registered
    # so scan-free runs read as zero series
    "scan_served",
    "scan_shed",
    "scan_keys",
    # autopilot policy tier (host/autopilot.py): actuations applied on
    # THIS server labeled by actuator, the announced driver mode
    # (0 = none/observe, 1 = act), and per-actuator remaining-cooldown
    # gauges — pre-registered so "no autopilot attached" reads as zero
    # series, not missing ones
    "autopilot_actions",
    "autopilot_mode",
    "autopilot_cooldown",
    # graftscope ring accounting (host/tracing.py): events the flight
    # ring overwrote, labeled by event type — pre-registered bare at
    # zero so "nothing dropped" reads as 0, not a missing series; the
    # labeled series appear per type once overflow actually happens
    "trace_dropped_total",
    # graftwatch streaming (host/graftwatch.py): delta frames emitted
    # over the ctrl plane, and the per-emit build cost — pre-registered
    # so streaming-off runs read as zero series, not missing ones
    "watch_frames_total",
    "watch_emit_us",
)

# canonical metric names every INGRESS PROXY (host/ingress.py) must
# expose — the proxy-tier twin of DECLARED, pre-registered at proxy
# construction so "never routed / never shed / never served a learner
# read" all read as zero series, not missing ones.  The proxy's embedded
# ExternalApi contributes the proxy_requests/replies/shed/queue_depth
# family through its metric namespace; the routing/dedupe/read-tier
# counters are the proxy's own.  Per-tier queue-depth attribution:
# ``api_queue_depth`` is the shard tier's gauge, ``proxy_queue_depth``
# the proxy tier's — overload location is readable straight off which
# tier's ``*_shed`` series moves.
PROXY_DECLARED = (
    "proxy_requests_total",
    "proxy_replies_total",
    "proxy_request_latency_us",
    "proxy_stamps_evicted",
    "proxy_shed",            # front-door sheds AT the proxy tier
    "proxy_queue_depth",
    "proxy_routed",          # commands forwarded to owner shards
    "proxy_dedupe_hits",     # (client, req_id) duplicates absorbed
    "proxy_upstream_shed",   # shard-tier sheds relayed through
    "proxy_backlog",         # internal forward backlog depth gauge
    "read_tier_served",      # reads served from the learner's state
    #                          (gets AND scans — total serve volume)
    "read_tier_scans",       # the scan share of read_tier_served
    "read_tier_backlog",     # in-flight freshness probes gauge
    "range_heat",            # per-key-range heat at the proxy seam
)
