"""graftprof: machine-produced performance attribution for the device
plane (CI tier 2h, committed baseline ``PROFILE.json``).

Every optimization claim in PERF.md used to come from hand-run
``scripts/profile_tick.py`` ablations pasted into prose, and the bench
trajectory was effectively ungated (BENCH_r05 shipped rc=1 with
0 slots/s and nothing noticed).  This module makes every hot-path
number machine-produced and regression-gateable:

- **Analytic cost model** — the per-tick XLA executable's
  ``cost_analysis()`` (flops / bytes accessed / transcendentals),
  ``memory_analysis()`` (argument / output / temp / generated-code
  bytes), compile wall time, and HLO instruction counts.  Deterministic
  per backend, so ``scripts/perf_gate.py`` gates them STRICTLY: a
  kernel edit that doubles the tick's flops fails CI even on a noisy
  box whose wall-clock could not resolve it.
- **Per-phase attribution** — kernels declare their named step phases
  in ``ProtocolKernel.PHASES`` (core/protocol.py); each phase runs
  under ``jax.named_scope(PHASE_SCOPE_PREFIX + name)``, the scope rides
  the jaxpr name stack into compiled-HLO ``op_name`` metadata, and this
  module recovers (a) HLO op counts per phase by parsing the optimized
  module text, and (b) MEASURED device time per phase by running the
  steady-state scan under ``jax.profiler.trace`` and joining each trace
  event's ``hlo_op`` back to its defining instruction's phase scope.
  The PERF.md breakdown table is generated from this, not maintained
  by hand.
- **Steady-state wall-clock** — best-of-N ``run_synthetic`` windows
  with shape-matched warmup (the two measurement bugs PERF.md round 2
  documents: warmup must hit the same static shape, and the first
  post-compile call carries one-time overhead).  Gated with a
  variance-aware tolerance + interleaved re-measure escalation, never
  strictly.
- **Instrumentation ablation** — ``named_scope`` is trace-time
  metadata, but the <5% overhead budget every observability plane in
  this repo carries (telemetry, tracing) is still measured, not
  assumed: interleaved scopes-on/scopes-off engine pairs via
  ``core.protocol.set_phase_scopes``.

All timing here uses the monotonic clock family
(``time.perf_counter``); ``host/profiling.py`` is registered in
graftlint's ``MONOTONIC_SCOPES``, so a wallclock read in this module is
an H103 finding.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import shutil
import tempfile
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core import Engine
from ..core.protocol import (
    PHASE_SCOPE_PREFIX,
    phase_scopes_enabled,
    set_phase_scopes,
)
from ..core.quorum import TALLY_MODES

PROFILE_VERSION = 1

#: the canonical capture set: the three protocols the acceptance gate
#: requires (MultiPaxos + Raft + the RS-coded MultiPaxos variant), each
#: at both config variants (device defaults / host-serving knobs).
CANONICAL_PROTOCOLS = ("multipaxos", "raft", "rspaxos")
CANONICAL_VARIANTS = ("device", "host")

#: canonical capture geometry — small enough that the full 3x2 cell
#: matrix plus the G-sweep compiles and runs in CI minutes on CPU,
#: large enough that G/R/W are mutually distinct and the window isn't
#: degenerate.  The committed PROFILE.json records the shape it was
#: captured at; perf_gate re-derives at the recorded shape.
CANONICAL_SHAPE: Dict[str, int] = {"G": 64, "R": 3, "W": 16}
CANONICAL_TICKS = 128
CANONICAL_REPS = 3
G_SWEEP = (16, 64, 256)

#: the pod-scale judging curve's mesh axis: the analytic G-sweep's twin
#: over mesh shapes at a FIXED global shape — per-device work should
#: fall ~linearly with group_shards while the HLO op count stays ~flat
#: (sharding changes WHERE the tick runs, not WHAT it computes), and
#: every sharded point must show the scan carry fully donated.  R=4 so
#: the 2x2 point truly splits the replica axis (in-group delivery
#: becomes a cross-device collective).
MESH_SWEEP = ("1x1", "2x1", "4x1", "2x2")
MESH_SWEEP_SHAPE: Dict[str, int] = {"G": 64, "R": 4, "W": 16}
MESH_SWEEP_TICKS = 32

#: the quorum-tally plane's before/after axis (core/quorum.py): per
#: (protocol, mesh shape, tally mode) analytic cells at the mesh-sweep
#: shape — the pairwise R² accept-reply lanes vs the collective
#: per-source records, with the tally phase's op count and the delay
#: line's lane shapes recorded so the perf gate can assert the
#: collective cells strictly shrink.  2x2 splits the replica axis, so
#: the collective point's lane delivery is a genuine cross-device
#: gather.  Crossword rides along: its shard-coverage quorums are the
#: largest win surface.
TALLY_SWEEP_PROTOCOLS = ("multipaxos", "crossword")
TALLY_SWEEP_MESHES = ("1x1", "2x2")

_PHASE_RE = re.compile(PHASE_SCOPE_PREFIX + r"(\w+)")
# one optimized-HLO instruction definition: "%name = ..." (ROOT or not),
# with its op_name metadata somewhere on the same line
_INSTR_RE = re.compile(r"^\s+(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_MODULE_RE = re.compile(r"HloModule\s+([^\s,]+)")


def _build_cell_kernel(name: str, variant: str, G: int, R: int, W: int):
    """One protocol x config-variant kernel at profile geometry —
    the same variant-flipping rules the graftlint verifier uses
    (``analysis/contract.build_kernel``), so 'host' means the same
    thing in LINT.json and PROFILE.json."""
    from ..analysis.contract import build_kernel
    from ..protocols import make_protocol

    return build_kernel(make_protocol, name, variant, G=G, R=R, W=W)


def _synth_inputs(kernel, proposals: int) -> Dict[str, Any]:
    """The per-tick input dict ``run_synthetic`` feeds the kernel —
    reproduced here so the analytic tick lowering sees the same shapes
    the measured scan does."""
    G, R = kernel.G, kernel.R
    return {
        "n_proposals": jnp.full((G,), proposals, jnp.int32),
        "value_base": jnp.zeros((G,), jnp.int32),
        "exec_floor": jnp.full((G, R), 1 << 30, jnp.int32),
    }


def _norm_cost(compiled) -> Dict[str, float]:
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    out = {}
    for key, label in (
        ("flops", "flops"),
        ("bytes accessed", "bytes_accessed"),
        ("transcendentals", "transcendentals"),
    ):
        v = ca.get(key)
        if v is not None:
            out[label] = round(float(v), 1)
    return out


def _mem_stats(compiled) -> Optional[Dict[str, int]]:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    return {
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
    }


_ALIAS_PAIR_RE = re.compile(
    r"\{(\d+)\}:\s*\((\d+),\s*\{\},\s*(?:may|must)-alias\)"
)


def donation_stats(compiled) -> Dict[str, Any]:
    """Carry-donation introspection for one compiled executable.

    ``aliased_buffers`` counts the ``input_output_alias`` pairs in the
    optimized HLO — the donation ground truth, and it SURVIVES the
    persistent compile cache.  The ``memory_analysis`` byte stats ride
    along (donated carry bytes must not be double-counted against
    output), but a cache-hit deserialized executable reports
    ``alias_bytes`` 0 — callers gate on the HLO pairs and treat the
    byte stats as fresh-compile-only corroboration."""
    pairs = _ALIAS_PAIR_RE.findall(compiled.as_text())
    out: Dict[str, Any] = {"aliased_buffers": len(pairs)}
    mem = _mem_stats(compiled)
    if mem is not None:
        out.update(
            argument_bytes=mem["argument_bytes"],
            alias_bytes=mem["alias_bytes"],
            output_bytes=mem["output_bytes"],
        )
    return out


def hlo_phase_ops(hlo_text: str) -> Tuple[int, Dict[str, int]]:
    """(total instruction count, per-phase instruction counts) from one
    optimized-HLO module text.  An instruction belongs to the phase its
    ``op_name`` metadata names via the ``PHASE_SCOPE_PREFIX`` scope;
    instructions without a phase scope (scan plumbing, netmodel
    delivery, parameter shuffling) are simply not attributed."""
    total = 0
    per_phase: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        total += 1
        om = _OPNAME_RE.search(line)
        if om is None:
            continue
        pm = _PHASE_RE.search(om.group(1))
        if pm is not None:
            per_phase[pm.group(1)] = per_phase.get(pm.group(1), 0) + 1
    return total, dict(sorted(per_phase.items()))


def hlo_op_phase_map(hlo_text: str) -> Tuple[Optional[str], Dict[str, str]]:
    """(module name, {instruction name -> phase}) — the join table for
    profiler trace events, whose ``args.hlo_op`` is the defining
    instruction's name.  Fusions carry their root op's scope, so a
    fusion straddling two phases attributes wholly to one of them; the
    residue is reported as ``unattributed`` rather than guessed."""
    mm = _MODULE_RE.search(hlo_text)
    module = mm.group(1) if mm else None
    opmap: Dict[str, str] = {}
    for line in hlo_text.splitlines():
        m = _INSTR_RE.match(line)
        if m is None:
            continue
        om = _OPNAME_RE.search(line)
        if om is None:
            continue
        pm = _PHASE_RE.search(om.group(1))
        if pm is not None:
            opmap[m.group(1)] = pm.group(1)
    return module, opmap


def attribute_trace_events(
    events: List[dict], opmap: Dict[str, str], module: Optional[str] = None
) -> Dict[str, float]:
    """Sum complete-event (``ph == "X"``) durations per phase.  Events
    whose ``hlo_op`` has no phase scope land in ``unattributed``; events
    from other modules (when ``module`` is given) are skipped."""
    acc: Dict[str, float] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        args = ev.get("args") or {}
        op = args.get("hlo_op")
        if op is None:
            continue
        if module is not None and args.get("hlo_module") not in (
            None, module
        ):
            continue
        phase = opmap.get(op, "unattributed")
        acc[phase] = acc.get(phase, 0.0) + float(ev.get("dur", 0.0))
    return acc


def capture_phase_walltime(
    compiled_text: str, run_fn, ticks: int
) -> Optional[Dict[str, float]]:
    """Measured device time per phase, in us/tick: run ``run_fn`` under
    ``jax.profiler.trace`` and attribute the captured per-op events via
    the compiled module's op->phase table.  Returns ``None`` when the
    backend's profiler is unavailable (the analytic metrics still
    stand); callers record that rather than failing."""
    module, opmap = hlo_op_phase_map(compiled_text)
    tmp = tempfile.mkdtemp(prefix="graftprof_")
    try:
        try:
            with jax.profiler.trace(tmp):
                run_fn()
        except Exception:
            return None
        files = glob.glob(
            os.path.join(tmp, "**", "*.trace.json.gz"), recursive=True
        )
        if not files:
            return None
        with gzip.open(files[0], "rt") as f:
            doc = json.load(f)
        acc = attribute_trace_events(
            doc.get("traceEvents", []), opmap, module
        )
        return {
            k: round(v / ticks, 3) for k, v in sorted(acc.items())
        }
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def measure_steady_tick(
    compiled, state, ns, ticks: int, reps: int = CANONICAL_REPS
):
    """Best-of-N steady-state seconds/tick for one AOT-compiled
    ``run_synthetic`` executable, plus the committed-slot rate over the
    best window and the final (state, ns) for further capture.

    Warmup discipline (PERF.md round 2): the executable is already
    shape-matched by construction (it IS the timed callable), and two
    untimed runs absorb the first-call transfer overhead and reach
    steady state before the clock starts."""
    import numpy as np

    for _ in range(2):
        state, ns = compiled(state, ns)
        jax.block_until_ready(state["commit_bar"])
    best = float("inf")
    best_rate = 0.0
    for _ in range(reps):
        start = np.asarray(state["commit_bar"]).max(axis=1).sum()
        t0 = time.perf_counter()
        state, ns = compiled(state, ns)
        jax.block_until_ready(state["commit_bar"])
        dt = time.perf_counter() - t0
        end = np.asarray(state["commit_bar"]).max(axis=1).sum()
        if dt < best:
            best = dt
            best_rate = float(end - start) / dt
    return best / ticks, best_rate, state, ns


def profile_cell(
    name: str,
    variant: str = "device",
    G: int = CANONICAL_SHAPE["G"],
    R: int = CANONICAL_SHAPE["R"],
    W: int = CANONICAL_SHAPE["W"],
    ticks: int = CANONICAL_TICKS,
    reps: int = CANONICAL_REPS,
    with_device_trace: bool = True,
    with_wall: bool = True,
) -> Dict[str, Any]:
    """One protocol x variant profile cell — the PROFILE.json unit."""
    kernel = _build_cell_kernel(name, variant, G, R, W)
    proposals = min(
        4, getattr(kernel.config, "max_proposals_per_tick", 4)
    )
    eng = Engine(kernel)
    state, ns = eng.init()

    # analytic per-tick metrics from the TICK module (scan-length-free,
    # so the strict gate compares like with like across shapes)
    inputs = _synth_inputs(kernel, proposals)
    t0 = time.perf_counter()
    tick_comp = eng.lower_tick(state, ns, inputs).compile()
    tick_compile_s = time.perf_counter() - t0
    tick_text = tick_comp.as_text()
    hlo_total, hlo_by_phase = hlo_phase_ops(tick_text)

    cell: Dict[str, Any] = {
        "protocol": name,
        "variant": variant,
        "shape": {"G": G, "R": R, "W": W, "P": proposals},
        "phases": [ph for ph, _ in kernel.PHASES],
        "analytic": dict(
            _norm_cost(tick_comp),
            hlo_instructions=hlo_total,
            hlo_ops_by_phase=hlo_by_phase,
        ),
        "memory": _mem_stats(tick_comp),
        "compile": {"tick_compile_s": round(tick_compile_s, 3)},
        "ok": True,
    }
    if not with_wall:
        return cell

    # steady-state wall-clock on the scanned executable
    t0 = time.perf_counter()
    scan_low = eng.lower_synthetic(state, ns, ticks, proposals)
    scan_comp = scan_low.compile()
    cell["compile"]["scan_compile_s"] = round(
        time.perf_counter() - t0, 3
    )
    s_per_tick, slots_per_s, state, ns = measure_steady_tick(
        scan_comp, state, ns, ticks, reps
    )
    cell["wall"] = {
        "s_per_tick": round(s_per_tick, 9),
        "ticks": ticks,
        "reps": reps,
        "committed_slots_per_s": round(slots_per_s, 1),
    }
    cell["ok"] = slots_per_s > 0

    if with_device_trace:
        scan_text = scan_comp.as_text()

        def run_once():
            out = scan_comp(state, ns)
            jax.block_until_ready(out[0]["commit_bar"])

        cell["phase_wall_us_per_tick"] = capture_phase_walltime(
            scan_text, run_once, ticks
        )
    return cell


def measure_scope_overhead(
    name: str = "multipaxos",
    G: int = CANONICAL_SHAPE["G"],
    R: int = CANONICAL_SHAPE["R"],
    W: int = CANONICAL_SHAPE["W"],
    ticks: int = CANONICAL_TICKS,
    pairs: int = 2,
    max_pairs: int = 4,
    max_pct: float = 5.0,
) -> Dict[str, Any]:
    """Instrumentation-ablation A/B: steady tick cost with phase scopes
    on vs compiled away (``set_phase_scopes``), as tightly interleaved
    pairs with best-of-side comparison — the same discipline the
    telemetry and tracing overhead gates use on this box.  Escalates
    (more pairs) while the apparent overhead exceeds ``max_pct``, so a
    single noisy window cannot fail CI by itself."""
    prior = phase_scopes_enabled()
    # the flag only matters at trace time: compile each side's scanned
    # executable ONCE under its flag, then every escalation round just
    # re-times the warm executables (no retrace/recompile per round)
    sides: Dict[bool, tuple] = {}
    try:
        for enabled in (True, False):
            set_phase_scopes(enabled)
            kernel = _build_cell_kernel(name, "device", G, R, W)
            proposals = min(
                4, getattr(kernel.config, "max_proposals_per_tick", 4)
            )
            eng = Engine(kernel)
            state, ns = eng.init()
            comp = eng.lower_synthetic(
                state, ns, ticks, proposals
            ).compile()
            sides[enabled] = (comp, state, ns)
    finally:
        set_phase_scopes(prior)

    results = {True: float("inf"), False: float("inf")}
    i = 0
    while True:
        i += 1
        for enabled in (True, False):
            comp, state, ns = sides[enabled]
            s_per_tick, _, state, ns = measure_steady_tick(
                comp, state, ns, ticks, reps=2
            )
            sides[enabled] = (comp, state, ns)
            results[enabled] = min(results[enabled], s_per_tick)
        pct = (
            (results[True] - results[False]) / results[False] * 100.0
            if results[False] > 0 else 0.0
        )
        if i >= pairs and (pct <= max_pct or i >= max_pairs):
            break
    return {
        "pct": round(pct, 2),
        "scopes_on_s_per_tick": round(results[True], 9),
        "scopes_off_s_per_tick": round(results[False], 9),
        "pairs": i,
    }


def analytic_block(
    kernel, proposals: Optional[int] = None
) -> Dict[str, Any]:
    """The graftprof stamp bench artifacts attach (bench.py /
    bench_tput_lat.py): analytic cost + memory + compile metrics for one
    tick at the bench's own shape — trajectory signal that stays
    meaningful even when the box's wall-clock is noisy."""
    if proposals is None:
        proposals = getattr(kernel.config, "max_proposals_per_tick", 4)
    eng = Engine(kernel)
    state, ns = eng.init()
    inputs = _synth_inputs(kernel, proposals)
    t0 = time.perf_counter()
    comp = eng.lower_tick(state, ns, inputs).compile()
    compile_s = time.perf_counter() - t0
    total, by_phase = hlo_phase_ops(comp.as_text())
    return {
        "shape": {
            "G": kernel.G, "R": kernel.R, "W": kernel.W, "P": proposals
        },
        "analytic": dict(
            _norm_cost(comp),
            hlo_instructions=total,
            hlo_ops_by_phase=by_phase,
        ),
        "memory": _mem_stats(comp),
        "tick_compile_s": round(compile_s, 3),
    }


def protocol_analytic_block(
    name: str, variant: str, G: int, R: int, W: int
) -> Dict[str, Any]:
    """:func:`analytic_block` for a registered protocol by name — the
    stamp the live-cluster bench artifacts (TPUTLAT/HOSTBENCH) attach,
    built with the same variant-flipping rules as the profile cells."""
    return analytic_block(_build_cell_kernel(name, variant, G, R, W))


def mesh_cell(
    name: str,
    spec: str,
    G: int = MESH_SWEEP_SHAPE["G"],
    R: int = MESH_SWEEP_SHAPE["R"],
    W: int = MESH_SWEEP_SHAPE["W"],
    ticks: int = MESH_SWEEP_TICKS,
    run_check: bool = True,
) -> Dict[str, Any]:
    """One mesh-shape point: the sharded engine's analytic tick metrics
    plus the donation introspection of its scanned executable.

    Everything recorded here is deterministic per backend (strictly
    gateable) EXCEPT ``committed_slots``, which exists only to prove the
    donated executable actually makes consensus progress — the gate
    asserts it is > 0 rather than comparing it."""
    import numpy as np

    from ..core import sharding as _shard

    gs, rs = _shard.parse_mesh(spec)
    mesh = _shard.mesh_for(gs, rs)
    kernel = _build_cell_kernel(name, "device", G, R, W)
    proposals = min(
        4, getattr(kernel.config, "max_proposals_per_tick", 4)
    )
    eng = Engine(kernel, mesh=mesh)  # sharded mode: carry donated
    state, ns = eng.init()
    carry_leaves = len(jax.tree.leaves((state, ns)))

    inputs = _synth_inputs(kernel, proposals)
    tick_comp = eng.lower_tick(state, ns, inputs).compile()
    hlo_total, _ = hlo_phase_ops(tick_comp.as_text())
    scan_comp = eng.lower_synthetic(state, ns, ticks, proposals).compile()
    don = donation_stats(scan_comp)

    cell: Dict[str, Any] = {
        **_shard.mesh_stamp(gs, rs, G),
        "analytic": dict(
            _norm_cost(tick_comp), hlo_instructions=hlo_total
        ),
        "memory": _mem_stats(tick_comp),
        # cache-stable donation facts only: alias BYTES read 0 on a
        # persistent-cache-hit executable, so the strict gate compares
        # the HLO alias-pair count against the carry leaf count instead
        "donation": {
            "aliased_buffers": don["aliased_buffers"],
            "carry_leaves": carry_leaves,
        },
        "donated": don["aliased_buffers"] == carry_leaves,
    }
    if run_check:
        state, ns = scan_comp(state, ns)
        state, ns = scan_comp(state, ns)
        jax.block_until_ready(state["commit_bar"])
        slots = int(np.asarray(state["commit_bar"]).max(axis=1).sum())
        cell["committed_slots"] = slots
        cell["ok"] = cell["donated"] and slots > 0
    else:
        cell["ok"] = cell["donated"]
    return cell


def mesh_sweep(
    name: str = "multipaxos",
    meshes: Tuple[str, ...] = MESH_SWEEP,
    G: int = MESH_SWEEP_SHAPE["G"],
    R: int = MESH_SWEEP_SHAPE["R"],
    W: int = MESH_SWEEP_SHAPE["W"],
    ticks: int = MESH_SWEEP_TICKS,
    run_check: bool = True,
    log=lambda m: None,
) -> Dict[str, Any]:
    """The mesh-shape twin of :func:`g_sweep` — one :func:`mesh_cell`
    per mesh spec at a fixed global shape, so the committed PROFILE.json
    carries a regression-gated multi-device trajectory even while the
    TPU tunnel is down (CPU runs use the virtual host-platform mesh).

    Shapes the visible pod cannot fit are recorded under ``skipped``
    (never silently dropped) rather than failing the sweep."""
    points = []
    skipped = []
    ndev = len(jax.devices())
    from ..core.sharding import parse_mesh

    for spec in meshes:
        gs, rs = parse_mesh(spec)
        if gs * rs > ndev:
            skipped.append({"mesh": spec, "reason": f"needs {gs * rs} "
                            f"devices, {ndev} visible"})
            continue
        log(f"mesh sweep {name} @ {spec} ...")
        points.append(
            mesh_cell(name, spec, G=G, R=R, W=W, ticks=ticks,
                      run_check=run_check)
        )
    return {
        "protocol": name,
        "variant": "device",
        "shape": {"G": G, "R": R, "W": W, "ticks": ticks},
        "points": points,
        "skipped": skipped,
    }


def tally_cell(
    name: str,
    tally: str,
    spec: str,
    G: int = MESH_SWEEP_SHAPE["G"],
    R: int = MESH_SWEEP_SHAPE["R"],
    W: int = MESH_SWEEP_SHAPE["W"],
    ticks: int = MESH_SWEEP_TICKS,
    with_device_trace: bool = False,
) -> Dict[str, Any]:
    """One (protocol, tally mode, mesh shape) point of the quorum-tally
    before/after (core/quorum.py).

    Deterministic per backend except ``committed_slots`` (a progress
    proof the gate re-asserts > 0 — and EQUAL across tally modes at the
    same point, the analytic face of the byte-identical equivalence
    gate) and the optional measured per-phase device time."""
    import numpy as np

    from ..core import sharding as _shard
    from ..core.quorum import PHASE_TALLY

    gs, rs = _shard.parse_mesh(spec)
    variant = "collective" if tally == "collective" else "device"
    kernel = _build_cell_kernel(name, variant, G, R, W)
    proposals = min(
        4, getattr(kernel.config, "max_proposals_per_tick", 4)
    )
    mesh = _shard.mesh_for(gs, rs) if gs * rs > 1 else None
    eng = Engine(kernel, mesh=mesh)
    state, ns = eng.init()
    # the acceptance-criterion lane geometry, straight off the delay
    # line: pairwise tally lanes are [D, G, R, R]; collective ones are
    # [D, G, R] — the R² pair-shaped enqueue is ABSENT
    lane_shapes = {
        lane: list(ns["bufs"][lane].shape)
        for lane in kernel.TALLY_LANES
    }

    inputs = _synth_inputs(kernel, proposals)
    tick_comp = eng.lower_tick(state, ns, inputs).compile()
    tick_text = tick_comp.as_text()
    hlo_total, by_phase = hlo_phase_ops(tick_text)

    cell: Dict[str, Any] = {
        "protocol": name,
        "tally": tally,
        **_shard.mesh_stamp(gs, rs, G),
        "analytic": dict(
            _norm_cost(tick_comp),
            hlo_instructions=hlo_total,
            tally_phase_ops=by_phase.get(PHASE_TALLY, 0),
        ),
        "hlo_ops_by_phase": by_phase,
        "memory": _mem_stats(tick_comp),
        "tally_lane_shapes": lane_shapes,
    }
    scan_comp = eng.lower_synthetic(state, ns, ticks, proposals).compile()
    state, ns = scan_comp(state, ns)
    state, ns = scan_comp(state, ns)
    jax.block_until_ready(state["commit_bar"])
    slots = int(np.asarray(state["commit_bar"]).max(axis=1).sum())
    cell["committed_slots"] = slots
    cell["ok"] = slots > 0
    if with_device_trace:
        scan_text = scan_comp.as_text()

        def run_once():
            out = scan_comp(state, ns)
            jax.block_until_ready(out[0]["commit_bar"])

        pw = capture_phase_walltime(scan_text, run_once, ticks)
        cell["phase_wall_us_per_tick"] = pw
        if pw:
            cell["tally_phase_wall_us"] = pw.get(PHASE_TALLY, 0.0)
    return cell


def tally_sweep(
    protocols: Tuple[str, ...] = TALLY_SWEEP_PROTOCOLS,
    meshes: Tuple[str, ...] = TALLY_SWEEP_MESHES,
    G: int = MESH_SWEEP_SHAPE["G"],
    R: int = MESH_SWEEP_SHAPE["R"],
    W: int = MESH_SWEEP_SHAPE["W"],
    ticks: int = MESH_SWEEP_TICKS,
    with_device_trace: bool = True,
    log=lambda m: None,
) -> Dict[str, Any]:
    """The quorum-tally before/after table (PROFILE.json
    ``tally_sweep``): every (protocol, mesh, tally mode) cell at the
    mesh-sweep shape.  Device-time capture runs on the single-device
    points only (multi-device CPU trace attribution is not stable
    enough to commit).  Shapes the pod cannot fit are recorded under
    ``skipped`` — never silently dropped."""
    from ..core.sharding import parse_mesh

    points = []
    skipped = []
    ndev = len(jax.devices())
    for name in protocols:
        for spec in meshes:
            gs, rs = parse_mesh(spec)
            if gs * rs > ndev:
                skipped.append({
                    "protocol": name, "mesh": spec,
                    "reason": f"needs {gs * rs} devices, {ndev} visible",
                })
                continue
            for tally in TALLY_MODES:
                log(f"tally sweep {name} @ {spec} [{tally}] ...")
                points.append(tally_cell(
                    name, tally, spec, G=G, R=R, W=W, ticks=ticks,
                    with_device_trace=(
                        with_device_trace and gs * rs == 1
                    ),
                ))
    return {
        "shape": {"G": G, "R": R, "W": W, "ticks": ticks},
        "points": points,
        "skipped": skipped,
    }


def g_sweep(
    name: str = "multipaxos",
    groups: Tuple[int, ...] = G_SWEEP,
    R: int = CANONICAL_SHAPE["R"],
    W: int = CANONICAL_SHAPE["W"],
) -> Dict[str, Any]:
    """Analytic-only sweep over the group axis: how flops / bytes /
    temp memory scale with G — the curve the pod-scale sharding PR will
    be judged against (strictly gateable; no wall-clock noise)."""
    points = []
    for G in groups:
        cell = profile_cell(
            name, "device", G=G, R=R, W=W,
            with_device_trace=False, with_wall=False,
        )
        points.append({
            "G": G,
            "flops": cell["analytic"].get("flops"),
            "bytes_accessed": cell["analytic"].get("bytes_accessed"),
            "hlo_instructions": cell["analytic"]["hlo_instructions"],
            "temp_bytes": (cell["memory"] or {}).get("temp_bytes"),
        })
    return {"protocol": name, "variant": "device", "points": points}


def build_profile(
    protocols: Tuple[str, ...] = CANONICAL_PROTOCOLS,
    variants: Tuple[str, ...] = CANONICAL_VARIANTS,
    G: int = CANONICAL_SHAPE["G"],
    R: int = CANONICAL_SHAPE["R"],
    W: int = CANONICAL_SHAPE["W"],
    ticks: int = CANONICAL_TICKS,
    reps: int = CANONICAL_REPS,
    with_overhead: bool = True,
    with_sweep: bool = True,
    with_mesh_sweep: bool = True,
    with_tally_sweep: bool = True,
    mesh_shapes: Optional[Tuple[str, ...]] = None,
    log=print,
) -> Dict[str, Any]:
    """The full PROFILE.json document (see scripts/profile_run.py)."""
    from ..protocols import protocol_display_name

    doc: Dict[str, Any] = {
        "version": PROFILE_VERSION,
        "generated_by": "scripts/profile_run.py",
        "backend": jax.devices()[0].platform,
        "jax_version": jax.__version__,
        "shape": {"G": G, "R": R, "W": W,
                  "ticks": ticks, "reps": reps},
        "protocols": {},
    }
    for name in protocols:
        disp = protocol_display_name(name)
        doc["protocols"][disp] = {}
        for variant in variants:
            log(f"profiling {disp} [{variant}] ...")
            cell = profile_cell(
                name, variant, G=G, R=R, W=W, ticks=ticks, reps=reps
            )
            doc["protocols"][disp][variant] = cell
    if with_sweep:
        log("g-sweep (analytic) ...")
        doc["g_sweep"] = g_sweep(protocols[0], R=R, W=W)
    if with_mesh_sweep:
        log("mesh sweep (analytic + donation) ...")
        doc["mesh_sweep"] = mesh_sweep(
            protocols[0], meshes=mesh_shapes or MESH_SWEEP, log=log
        )
    if with_tally_sweep:
        log("quorum-tally sweep (pairwise vs collective) ...")
        doc["tally_sweep"] = tally_sweep(log=log)
    if with_overhead:
        log("phase-scope overhead ablation A/B ...")
        doc["scope_overhead"] = measure_scope_overhead(
            protocols[0], G=G, R=R, W=W, ticks=ticks
        )
    doc["profiler_available"] = any(
        cell.get("phase_wall_us_per_tick") is not None
        for per in doc["protocols"].values() for cell in per.values()
    )
    return doc


def phase_table_markdown(doc: Dict[str, Any]) -> str:
    """The PERF.md breakdown table, generated from a PROFILE.json doc
    (rounds >= 9 are produced by this, not by hand)."""
    lines = [
        "| Protocol (variant) | ms/tick | top phases by measured device "
        "time (us/tick) | HLO ops |",
        "|---|---|---|---|",
    ]
    for proto, per in sorted(doc.get("protocols", {}).items()):
        for variant, cell in sorted(per.items()):
            wall = cell.get("wall") or {}
            ms = (wall.get("s_per_tick") or 0.0) * 1e3
            pw = cell.get("phase_wall_us_per_tick") or {}
            top = sorted(
                ((k, v) for k, v in pw.items() if k != "unattributed"),
                key=lambda kv: -kv[1],
            )[:3]
            tops = ", ".join(f"{k} {v:.0f}" for k, v in top) or "n/a"
            lines.append(
                f"| {proto} ({variant}) | {ms:.3f} | {tops} | "
                f"{cell['analytic']['hlo_instructions']} |"
            )
    return "\n".join(lines)
