"""Crossword host-side adaptive shard-assignment policy.

Parity: reference ``src/protocols/crossword/adaptive.rs:274+`` — per-peer
linear-regression perf models (payload size -> delivery time,
``utils/linreg.rs``) folded with netem qdisc introspection
(``utils/qdisc.rs``) drive the shards-per-replica choice pushed into the
Accept path (``crossword/mod.rs:1141-1145``).

TPU-native split: the device kernel owns the *reactive* policy (per-peer
lag counters widening ``cur_spr``, crossword.py); this module is the
*predictive* override — the host samples per-peer frame delivery times
(frames carry a send timestamp; CLOCK_MONOTONIC is machine-wide, and
cross-host deployments fall back to the kernel's reactive policy), fits a
PerfModel per peer, optionally folds the local interface's netem state,
and computes the ``spr_override`` kernel input: the widest assignment
whose predicted critical-path delivery beats the full-copy baseline.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..utils.linreg import LinearRegressor, PerfModel
from ..utils.qdisc import QdiscInfo


class CrosswordAdaptive:
    def __init__(
        self,
        population: int,
        data_shards: int,
        me: int,
        dev: Optional[str] = None,
        window_ms: float = 5000.0,
        refit_interval: float = 0.5,
    ):
        self.R = population
        self.d = data_shards
        self.me = me
        self.window_ms = window_ms
        self.refit_interval = refit_interval
        self._reg: Dict[int, LinearRegressor] = {
            p: LinearRegressor() for p in range(population) if p != me
        }
        self._model: Dict[int, PerfModel] = {
            p: PerfModel() for p in range(population) if p != me
        }
        self._qdisc = QdiscInfo(dev)
        self._last_fit = 0.0
        self._fitted: set = set()

    def observe(self, peer: int, nbytes: float, delay_ms: float) -> None:
        """One delivery sample: a frame of ``nbytes`` from ``peer`` took
        ``delay_ms`` (send-stamp to receive; clock-skew-free on one
        machine)."""
        reg = self._reg.get(peer)
        if reg is None or delay_ms < 0:
            return
        now_ms = time.monotonic() * 1e3
        reg.append_sample(now_ms, nbytes, delay_ms)
        reg.discard_before(now_ms - self.window_ms)

    def _refit(self) -> None:
        now = time.monotonic()
        if now - self._last_fit < self.refit_interval:
            return
        self._last_fit = now
        self._qdisc.update()
        for p, reg in self._reg.items():
            fit = reg.calc_model()
            if fit is not None:
                self._model[p].update(*fit)
                self._fitted.add(p)

    def predict_ms(self, peer: int, nbytes: float) -> float:
        """Predicted delivery time for ``nbytes`` to ``peer``, with the
        local netem delay/rate folded in (adaptive.rs folds QdiscInfo the
        same way)."""
        m = self._model.get(peer)
        base = m.predict(nbytes) if m is not None else 0.0
        q = self._qdisc
        return base + q.delay_ms + (
            nbytes * 8e-9 / q.rate_gbps * 1e3 if q.rate_gbps > 0 else 0.0
        )

    def choose_spr(self, batch_bytes: float) -> int:
        """Pick shards-per-replica: the narrowest assignment whose
        predicted slowest-of-(commit quorum - 1) peer delivery does not
        lose to shipping full copies (spr = d).  Mirrors the reference's
        tradeoff: narrower shards -> less data per peer but a larger
        commit quorum (crossword/mod.rs:324-396 commit condition)."""
        self._refit()
        peers = sorted(self._reg)
        if not peers or batch_bytes <= 0 or not self._fitted:
            return self.d  # no evidence yet: full copies are always safe
        shard = batch_bytes / max(self.d, 1)
        best_spr, best_t = self.d, None
        majority = self.R // 2 + 1
        for spr in range(1, self.d + 1):
            # commit needs majority + (d - spr) acks (generalized quorum);
            # critical path = the k-th fastest peer delivery of spr shards
            k = min(majority + (self.d - spr) - 1, len(peers))
            if k <= 0:
                continue
            times = sorted(
                self.predict_ms(p, shard * spr) for p in peers
            )
            t = times[k - 1]
            if best_t is None or t < best_t:
                best_spr, best_t = spr, t
        return best_spr

    def overrides(self, num_groups: int, batch_bytes: float) -> List[int]:
        """The ``spr_override`` kernel input: one choice broadcast to all
        groups (the host observes one shared TCP mesh)."""
        return [self.choose_spr(batch_bytes)] * num_groups
