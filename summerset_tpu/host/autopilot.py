"""Autopilot control plane: close the sense -> decide -> actuate loop.

PR 10 built the senses (quorum-median health verdicts riding ``hb``
beacons, per-tier shed/queue telemetry, per-phase perf attribution) and
PRs 7/10/13/16 grew the actuators (voluntary leader demotion, QL/Bodega
responder ConfChange, ``api_max_batch``, ``pipeline``, range splits via
``ResharderPolicy``) — but every policy was static: a workload or fault
shift meant a human re-running a driver with different flags.  This
module is the policy tier that turns those knobs continuously:

- :class:`AutopilotPolicy` — the SEEDED decision core.  A pure function
  of (seed, the senses sequence fed to :meth:`~AutopilotPolicy.evaluate`):
  no wallclock, no unseeded RNG (graftlint ``SEEDED_SCOPES`` membership,
  the FaultPlan/WorkloadPlan repro contract).  Time is the round counter
  — one ``evaluate`` call per scrape round.  Every actuator is
  deliberately conservative in the PR 10 style: hysteresis streaks (an
  oscillating signal flaps the streak, not the cluster), per-actuator
  cooldowns, quorum-gated evaluation (no quorum => no actuation, streaks
  RESET so churn windows cannot bank hysteresis credit), a bounded
  actuation budget per window, and at most one change per group per
  window (the reshard-vs-lead_move race guard).  Decisions accumulate
  into a canonical :meth:`~AutopilotPolicy.timeline` with a sha256
  :meth:`~AutopilotPolicy.digest` — the decision-trace analog of
  ``FaultPlan.timeline()``.
- :class:`AutopilotDriver` — the wall-clock half.  Scrapes the senses on
  a cadence (``metrics_dump`` partial-tolerant gathers + ``query_info``),
  folds them into the canonical senses dict (:func:`build_senses`), and
  in ``mode="act"`` lowers fired decisions onto the EXISTING ctrl plane:
  ``autopilot_ctl`` fan-outs (targeted demotion, live ``api_max_batch``
  / ``pipeline`` retune), ``range_change`` (reshard), and injectable
  ``conf_ctl`` / ``proxy_ctl`` hooks (responder resize, proxy scaling).
  ``mode="observe"`` evaluates and logs decisions but sends ZERO ctrl
  mutations — byte-identical to no autopilot at all on the same seed,
  the twin-soak control cell.

Actuator -> signal -> lowering:

====  ============  =======================  ===========================
act   actuator      fires on                 lowered as
====  ============  =======================  ===========================
1     lead_move     leader health_score low  ``autopilot_ctl {demote}``
                    OR ingress/leader        to the leader (reuses the
                    affinity mismatch        health plane's revoke-then-
                                             demote machinery)
2     batch         shed-rate EWMA high /    ``autopilot_ctl {retune
                    idle                     api_max_batch}`` fan-out
3     pipeline      shed persists at         ``autopilot_ctl {retune
                    batch_max, serial loop   pipeline}`` fan-out
4     conf_resize   key-heat concentration   ``conf_ctl(responders)``
                    (lease protocols only)   hook (client ConfChange)
5     reshard       embedded ResharderPolicy ``range_change`` ctrl req
                    (decisions flow through  (the PR 16 seal/adopt
                    THIS policy's budget)    cutover)
6     recommend     overload survives every  log-only: ``tally`` /
                    live knob                ``wire_codec`` are compile-
                                             time retunes
====  ============  =======================  ===========================

The batch ladder is smoothed through an EWMA of the shed rate — the
in-tree predictive-refit template is ``host/adaptive.py``'s
CrosswordAdaptive (sample -> refit -> override), shrunk to one scalar
model since the shed signal is already a rate.

Related work: compartmentalized SMR (arxiv 2012.15762) motivates
re-sizing serving compartments (responder sets, batch capacity, proxy
count) as load moves; arxiv 1905.10786 frames porting the same policies
across the kernel families (the demote actuator degrades to score-only
on families without the ``demote`` input, exactly like the health
plane).
"""

from __future__ import annotations

import dataclasses
import hashlib
import random
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .messages import CtrlRequest
from .resharding import RangeHeat, ResharderPolicy
from ..utils.logging import pf_info, pf_logger, pf_warn

logger = pf_logger("autopilot")

#: actuator label vocabulary (``autopilot_actions`` counter labels and
#: the per-actuator cooldown gauges)
ACTUATORS = (
    "lead_move", "batch", "pipeline", "conf_resize", "reshard",
    "recommend",
)

#: kernel families whose conf plane carries lease responder sets (the
#: conf_resize actuator is a no-op elsewhere)
LEASE_PROTOCOLS = ("quorumleases", "bodega")

#: graftwatch SLO objective -> actuator lowering: a latched burn-rate
#: alert that persists a full streak drives the EXISTING actuators
#: through the same admission/budget/fire path as every other signal.
#: Latency and shed burns escalate the batch ladder; a WAL-fsync burn
#: indicts the leader's disk (the fail-slow demote path); a scan-
#: starvation burn has no live knob — it lowers as a log-only
#: recommendation (route scans to the learner tier).
SLO_ACTUATORS = {
    "reply_p99": "batch",
    "shed_rate": "batch",
    "wal_fsync_lag": "lead_move",
    "scan_starvation": "recommend",
}


@dataclasses.dataclass(frozen=True)
class Decision:
    """One fired (or recommended) actuation — the decision-trace unit.

    ``target`` is a server id where the act is targeted (the demotee for
    ``lead_move``); ``arg`` is the actuator-specific operand (new batch
    size, responder list, ``RangeChange.as_dict()``...).  ``render`` is
    the canonical one-line form the timeline/digest is built from, so
    every field that matters to repro must appear in it.
    """

    round_no: int
    actuator: str
    group: int
    target: Optional[int]
    arg: Any
    reason: str

    def render(self) -> str:
        tgt = "-" if self.target is None else str(self.target)
        return (f"r{self.round_no:04d} {self.actuator:<11s} "
                f"g{self.group} t{tgt} arg={self.arg!r} [{self.reason}]")


@dataclasses.dataclass
class ActuatorState:
    """Per-actuator hysteresis bookkeeping: a signed streak (positive =
    escalate pressure, negative = relax pressure), the round the
    cooldown holds until (exclusive), and the lifetime fire count."""

    streak: int = 0
    cooldown_until: int = -1
    fires: int = 0


class AutopilotPolicy:
    """Seeded-deterministic sense->decision core (see module docstring).

    The policy holds NO sockets and reads NO clocks: callers feed one
    senses dict per round (``evaluate``) and receive the decisions that
    fired.  The only RNG is seeded (successor tie-breaks), so the same
    seed + the same senses sequence yields a byte-identical decision
    timeline — the gate and the unit tests both lean on that.
    """

    def __init__(
        self,
        seed: int,
        population: int,
        num_groups: int = 1,
        streak_need: int = 3,
        cooldown_rounds: int = 10,
        window_rounds: int = 8,
        budget_per_window: int = 2,
        shed_hi: float = 0.05,
        shed_lo: float = 0.005,
        shed_alpha: float = 0.5,
        batch_max: int = 16,
        health_bad: float = 0.5,
        affinity_frac: float = 0.6,
        min_ingress: int = 20,
        lease_hot_share: float = 0.5,
        lease_flat_share: float = 0.15,
        heat_min: int = 20,
        resharder: Optional[ResharderPolicy] = None,
    ):
        self.seed = int(seed)
        self.population = int(population)
        self.G = int(num_groups)
        self.streak_need = max(1, int(streak_need))
        self.cooldown_rounds = max(0, int(cooldown_rounds))
        self.window_rounds = max(1, int(window_rounds))
        self.budget_per_window = max(0, int(budget_per_window))
        self.shed_hi = float(shed_hi)
        self.shed_lo = float(shed_lo)
        self.shed_alpha = float(shed_alpha)
        self.batch_max = int(batch_max)
        self.health_bad = float(health_bad)
        self.affinity_frac = float(affinity_frac)
        self.min_ingress = int(min_ingress)
        self.lease_hot_share = float(lease_hot_share)
        self.lease_flat_share = float(lease_flat_share)
        self.heat_min = int(heat_min)
        # seeded RNG: the only nondeterminism budget (successor
        # tie-breaks); salted so policy draws differ from the nemesis/
        # workload generators sharing a seed in one soak cell
        self.rng = random.Random((self.seed << 8) ^ 0x417)
        self.resharder = resharder
        if resharder is not None:
            # satellite bugfix (PR 17): ResharderPolicy decisions flow
            # through THIS policy's budget — a reshard storm can no
            # longer race a leader re-placement on the same group
            resharder.budget_gate = (
                lambda g: self._admit("reshard", int(g))
            )
        self._acts: Dict[str, ActuatorState] = {
            a: ActuatorState() for a in ACTUATORS
        }
        self._round = -1
        self._win = -1
        self._win_spend = 0
        #: high-water mark of per-window spend — the committed soak row
        #: records it so the gate can check the budget was never blown
        self.max_window_spend = 0
        self._group_round: Dict[int, int] = {}
        self._decisions: List[Decision] = []
        self._shed_ewma = 0.0
        self._batch_base: Optional[int] = None
        self._recommended = False
        self.last_quorum = False
        # graftwatch burn-alert streaks, per objective name.  Kept OFF
        # config_line(): a policy evaluated without slo_burn senses
        # renders and digests byte-identically to one built before the
        # graftwatch plane existed (the committed AUTOPILOT.json drift
        # gate regenerates digests from this code)
        self._slo_streaks: Dict[str, int] = {}

    # ------------------------------------------------------- admission
    def _admit(self, actuator: str, group: int) -> bool:
        """Cooldown + window budget + one-change-per-group admission.
        Shared by every actuator AND (via ``budget_gate``) by an
        embedded ResharderPolicy, so all actuation paths answer to one
        budget."""
        st = self._acts[actuator]
        if self._round < st.cooldown_until:
            return False
        if self._win_spend >= self.budget_per_window:
            return False
        last = self._group_round.get(int(group))
        if last is not None and self._round - last < self.window_rounds:
            return False
        return True

    def _fire(self, actuator: str, group: int, target: Optional[int],
              arg: Any, reason: str) -> Decision:
        st = self._acts[actuator]
        st.streak = 0
        st.cooldown_until = self._round + self.cooldown_rounds
        st.fires += 1
        self._win_spend += 1
        self.max_window_spend = max(self.max_window_spend,
                                    self._win_spend)
        self._group_round[int(group)] = self._round
        d = Decision(self._round, actuator, int(group), target, arg,
                     reason)
        self._decisions.append(d)
        return d

    def cooldowns(self) -> Dict[str, int]:
        """Remaining cooldown rounds per actuator (0 = armed)."""
        return {
            a: max(0, st.cooldown_until - self._round)
            for a, st in self._acts.items()
        }

    def fires(self) -> Dict[str, int]:
        return {a: st.fires for a, st in self._acts.items()}

    # ------------------------------------------------------- evaluate
    def evaluate(self, senses: Dict[str, Any]) -> List[Decision]:
        """One decision round over one senses dict; returns the
        decisions that fired this round (possibly empty)."""
        self._round += 1
        win = self._round // self.window_rounds
        if win != self._win:
            self._win = win
            self._win_spend = 0
        out: List[Decision] = []
        pop = int(senses.get("population", self.population))
        alive = int(senses.get("alive", 0))
        leader = senses.get("leader")
        self.last_quorum = (
            alive >= pop // 2 + 1 and leader is not None
        )
        if not self.last_quorum:
            # no quorum => no actuation, and streaks RESET: an election-
            # churn window must not bank hysteresis credit that fires
            # the instant quorum returns
            for st in self._acts.values():
                st.streak = 0
            return out
        leader = int(leader)
        health = dict(senses.get("health") or {})
        ingress = {
            int(s): float(n)
            for s, n in (senses.get("ingress") or {}).items()
        }
        shed = float(senses.get("shed_rate", 0.0))
        self._shed_ewma = (
            self.shed_alpha * shed
            + (1.0 - self.shed_alpha) * self._shed_ewma
        )
        cur_batch = int(senses.get("api_max_batch", 0) or 0)
        if cur_batch and self._batch_base is None:
            self._batch_base = cur_batch

        out.extend(self._eval_lead_move(leader, health, ingress))
        out.extend(self._eval_batch(cur_batch))
        out.extend(self._eval_pipeline(senses, cur_batch))
        out.extend(self._eval_conf_resize(senses, leader, ingress))
        out.extend(self._eval_reshard(senses))
        out.extend(self._eval_recommend(senses, cur_batch))
        out.extend(self._eval_slo_burn(senses, leader))
        return out

    # ------------------------------------------------- actuator rules
    def _eval_lead_move(self, leader: int, health: Dict[Any, float],
                        ingress: Dict[int, float]) -> List[Decision]:
        """Re-place leadership near health and traffic: fires when the
        leader's own health verdict is bad (fail-slow) or when a
        dominant share of ingress lands on a healthy non-leader (the
        affinity flip)."""
        bad = float(health.get(leader, 1.0)) <= self.health_bad
        total_in = sum(ingress.values())
        top = None
        if total_in >= self.min_ingress:
            top = min(ingress, key=lambda s: (-ingress[s], s))
        affinity_off = (
            top is not None and top != leader
            and ingress[top] >= self.affinity_frac * total_in
            and float(health.get(top, 1.0)) > self.health_bad
        )
        st = self._acts["lead_move"]
        if bad or affinity_off:
            st.streak = max(1, st.streak + 1)
        else:
            st.streak = 0
        if st.streak < self.streak_need \
                or not self._admit("lead_move", 0):
            return []
        # preferred successor: the affinity target when the signal is
        # affinity; otherwise a seeded pick among healthy non-leaders
        # (advisory — the kernel's own election decides)
        if affinity_off:
            succ = int(top)
        else:
            cands = sorted(
                int(s) for s, sc in health.items()
                if int(s) != leader and float(sc) > self.health_bad
            )
            succ = self.rng.choice(cands) if cands else None
        reason = "leader-unhealthy" if bad else "leader-affinity"
        return [self._fire("lead_move", 0, leader, succ, reason)]

    def _eval_batch(self, cur: int) -> List[Decision]:
        """Shed-rate EWMA drives the ``api_max_batch`` ladder: sustained
        shedding doubles it (up to ``batch_max``); a sustained idle
        signal steps it back down toward the configured baseline —
        never below it, so the autopilot cannot starve a deliberately
        small ingress tier."""
        if not cur:
            return []
        st = self._acts["batch"]
        base = self._batch_base or cur
        if self._shed_ewma >= self.shed_hi and cur < self.batch_max:
            st.streak = max(1, st.streak + 1)
        elif self._shed_ewma <= self.shed_lo and cur > base:
            st.streak = min(-1, st.streak - 1)
        else:
            st.streak = 0
        if st.streak >= self.streak_need and self._admit("batch", 0):
            arg = min(cur * 2, self.batch_max)
            return [self._fire(
                "batch", 0, None, arg,
                f"shed_ewma={self._shed_ewma:.3f}",
            )]
        if st.streak <= -self.streak_need and self._admit("batch", 0):
            arg = max(cur // 2, base)
            return [self._fire("batch", 0, None, arg, "idle")]
        return []

    def _eval_pipeline(self, senses: Dict[str, Any],
                       cur_batch: int) -> List[Decision]:
        """Flip the pipelined tick loop on when shedding persists with
        the batch ladder exhausted — the remaining live throughput
        lever before compile-time recommendations."""
        st = self._acts["pipeline"]
        pipe = senses.get("pipeline")
        if (pipe is False and cur_batch >= self.batch_max
                and self._shed_ewma >= self.shed_hi):
            st.streak = max(1, st.streak + 1)
        else:
            st.streak = 0
        if st.streak >= self.streak_need \
                and self._admit("pipeline", 0):
            return [self._fire("pipeline", 0, None, True,
                               "shed-at-batch-max")]
        return []

    def _eval_conf_resize(self, senses: Dict[str, Any], leader: int,
                          ingress: Dict[int, float]) -> List[Decision]:
        """QL/Bodega lease-responder sizing per key-range heat:
        concentrated heat shrinks the responder set to {leader, hottest
        ingress replica} (fewer lease grants to revoke per write of a
        hot key); flat heat widens it back out (reads everywhere)."""
        if not senses.get("lease_protocol"):
            return []
        resp = senses.get("responders")
        if resp is None:
            return []
        resp = sorted(int(r) for r in resp)
        heat = {
            k: float(v)
            for k, v in (senses.get("heat") or {}).items()
            if k != RangeHeat.SPILL
        }
        total = sum(heat.values())
        top_share = (
            max(heat.values()) / total if total > 0 else 0.0
        )
        sids = sorted(
            int(s) for s in (senses.get("sids") or
                             range(self.population))
        )
        st = self._acts["conf_resize"]
        target: Optional[List[int]] = None
        reason = ""
        if (total >= self.heat_min
                and top_share >= self.lease_hot_share
                and len(resp) > 2):
            hot_sid = (
                min(ingress, key=lambda s: (-ingress[s], s))
                if ingress else leader
            )
            target = sorted({leader, int(hot_sid)})
            reason = f"heat-concentrated({top_share:.2f})"
        elif (total >= self.heat_min
                and top_share <= self.lease_flat_share
                and len(resp) < len(sids)):
            target = sids
            reason = f"heat-flat({top_share:.2f})"
        if target is not None and target != resp:
            st.streak = max(1, st.streak + 1)
        else:
            st.streak = 0
            return []
        if st.streak >= self.streak_need \
                and self._admit("conf_resize", 0):
            return [self._fire("conf_resize", 0, None, target, reason)]
        return []

    def _eval_reshard(self, senses: Dict[str, Any]) -> List[Decision]:
        """Heat-driven placement through the embedded ResharderPolicy.
        The heat signal must persist a full streak before ``decide`` is
        even consulted, and ``decide`` itself answers to this policy's
        budget via ``budget_gate`` — so a heat spike and a health
        indictment cannot both actuate on one group in one window."""
        if self.resharder is None:
            return []
        pol = self.resharder
        heat = {
            k: int(v) for k, v in (senses.get("heat") or {}).items()
        }
        live = {k: n for k, n in heat.items() if k != RangeHeat.SPILL}
        total = sum(live.values())
        hot = any(
            k not in pol._moved and n >= pol.hot_frac * total
            for k, n in live.items()
        ) if total >= pol.min_total else False
        cold = any(
            k in pol._moved and n <= pol.cold_frac * total
            for k, n in live.items()
        ) if total >= pol.min_total else False
        st = self._acts["reshard"]
        if hot or cold:
            st.streak = max(1, st.streak + 1)
        else:
            st.streak = 0
        if st.streak < self.streak_need:
            return []
        ch = pol.decide(heat)
        if ch is None:
            return []
        return [self._fire("reshard", int(ch.dst_group), None,
                           ch.as_dict(), ch.op)]

    def _eval_recommend(self, senses: Dict[str, Any],
                        cur_batch: int) -> List[Decision]:
        """Log-only compile-time recommendations: when overload survives
        every live knob (batch at max, pipeline on, shed EWMA still
        high), recommend the tally/wire_codec retunes a redeploy would
        apply.  Fires once per policy lifetime and spends no budget —
        there is nothing to actuate."""
        if self._recommended:
            return []
        st = self._acts["recommend"]
        if (cur_batch >= self.batch_max
                and senses.get("pipeline") is True
                and self._shed_ewma >= self.shed_hi):
            st.streak = max(1, st.streak + 1)
        else:
            st.streak = 0
        if st.streak < 2 * self.streak_need:
            return []
        self._recommended = True
        st.fires += 1
        d = Decision(
            self._round, "recommend", 0, None,
            {"tally": "hierarchical", "wire_codec": True},
            "overload-survives-live-knobs",
        )
        self._decisions.append(d)
        return [d]

    def _eval_slo_burn(self, senses: Dict[str, Any],
                       leader: int) -> List[Decision]:
        """graftwatch burn-rate alerts as a sense input: each LATCHED
        alert (fast AND slow burn over the policy's hi bound —
        host/graftwatch.py SloPolicy) must persist a full streak of
        rounds, then lowers through :data:`SLO_ACTUATORS` under the
        same admission gates as every native signal.  INERT without
        the ``slo_burn`` sense key: no streak state moves, no RNG
        draw happens, so a driver without a graftwatch attachment
        evaluates byte-identically to pre-graftwatch code."""
        burns = senses.get("slo_burn")
        if not burns:
            return []
        out: List[Decision] = []
        cur_batch = int(senses.get("api_max_batch", 0) or 0)
        for name in sorted(burns):
            row = burns[name] or {}
            streak = (
                self._slo_streaks.get(name, 0) + 1
                if row.get("alerting") else 0
            )
            self._slo_streaks[name] = streak
            if streak < self.streak_need:
                continue
            actuator = SLO_ACTUATORS.get(name)
            reason = (f"slo:{name} fast={row.get('fast')} "
                      f"slow={row.get('slow')}")
            if actuator == "batch":
                if not cur_batch or cur_batch >= self.batch_max \
                        or not self._admit("batch", 0):
                    continue
                self._slo_streaks[name] = 0
                arg = min(cur_batch * 2, self.batch_max)
                out.append(self._fire("batch", 0, None, arg, reason))
                cur_batch = arg
            elif actuator == "lead_move":
                if not self._admit("lead_move", 0):
                    continue
                self._slo_streaks[name] = 0
                # successor deliberately unspecified (no RNG draw —
                # the kernel's own election decides): the signal is
                # "this leader's durability path is burning budget",
                # not a placement preference
                out.append(self._fire(
                    "lead_move", 0, leader, None, reason
                ))
            elif actuator == "recommend":
                if self._recommended:
                    continue
                self._slo_streaks[name] = 0
                self._recommended = True
                st = self._acts["recommend"]
                st.fires += 1
                d = Decision(
                    self._round, "recommend", 0, None,
                    {"scan_tier": "learner"}, reason,
                )
                self._decisions.append(d)
                out.append(d)
        return out

    # -------------------------------------------------- decision trace
    def decisions(self) -> List[Decision]:
        return list(self._decisions)

    def config_line(self) -> str:
        """The canonical knob rendering — the static half of the
        timeline, regenerable by the gate without replaying senses."""
        return (
            f"autopilot seed={self.seed} pop={self.population} "
            f"G={self.G} streak={self.streak_need} "
            f"cooldown={self.cooldown_rounds} "
            f"window={self.window_rounds} "
            f"budget={self.budget_per_window} "
            f"shed=[{self.shed_lo},{self.shed_hi}] "
            f"batch_max={self.batch_max} "
            f"health_bad={self.health_bad} "
            f"affinity={self.affinity_frac}"
        )

    def timeline(self) -> str:
        lines = [self.config_line()]
        lines.extend(d.render() for d in self._decisions)
        return "\n".join(lines) + "\n"

    def digest(self) -> str:
        return hashlib.sha256(self.timeline().encode()).hexdigest()[:16]

    def config_digest(self) -> str:
        return hashlib.sha256(
            self.config_line().encode()
        ).hexdigest()[:16]


# ---------------------------------------------------------------- senses
def build_senses(snaps: Dict[str, dict], info: Any,
                 prev: Optional[dict]) -> Tuple[dict, dict]:
    """Fold one ``metrics_dump`` gather + one ``query_info`` reply into
    the canonical senses dict, computing per-interval DELTAS against the
    previous scrape's cumulative counters (cumulative series never cool;
    the delta is the live signal — the run_reshard_ab convention).

    Returns ``(senses, cursor)``; pass ``cursor`` back as ``prev`` on
    the next round.  Shared by the driver and the twin soak so both
    sides of an A/B sense identically.
    """
    cur = {"req": {}, "shed": {}, "heat": {}}
    health: Dict[int, float] = {}
    queue = 0.0
    batch = 0
    pipeline = None
    protocol = ""
    for sid_s, snap in (snaps or {}).items():
        sid = int(sid_s)
        host = snap.get("host", {}) or {}
        ctr = host.get("counters", {}) or {}
        gag = host.get("gauges", {}) or {}
        cur["req"][sid] = int(ctr.get("api_requests_total", 0))
        cur["shed"][sid] = int(ctr.get("api_shed", 0))
        for name, v in gag.items():
            if name.startswith("range_heat{key="):
                k = name[len("range_heat{key="):-1]
                cur["heat"][k] = cur["heat"].get(k, 0) + int(v)
        health[sid] = float(gag.get("health_score", 1.0))
        queue = max(queue, float(gag.get("api_queue_depth", 0.0)))
        batch = max(batch, int(snap.get("api_max_batch", 0) or 0))
        if pipeline is None:
            pipeline = bool(snap.get("pipeline", False))
        protocol = str(snap.get("protocol", protocol))
    prev = prev or {"req": {}, "shed": {}, "heat": {}}
    d_req = {
        sid: max(0, n - int(prev["req"].get(sid, 0)))
        for sid, n in cur["req"].items()
    }
    d_shed = sum(
        max(0, n - int(prev["shed"].get(sid, 0)))
        for sid, n in cur["shed"].items()
    )
    d_heat = {
        k: max(0, n - int(prev["heat"].get(k, 0)))
        for k, n in cur["heat"].items()
    }
    arrivals = sum(d_req.values())
    senses = {
        "population": len(getattr(info, "servers", None) or {})
        or len(snaps or {}),
        "alive": len(snaps or {}),
        "leader": getattr(info, "leader", None),
        "health": health,
        "ingress": d_req,
        "shed_rate": d_shed / arrivals if arrivals > 0 else 0.0,
        "queue_depth": queue,
        "api_max_batch": batch,
        "pipeline": pipeline,
        "heat": d_heat,
        "lease_protocol": (
            protocol.replace("_", "").lower() in LEASE_PROTOCOLS
        ),
        "responders": None,
        "sids": sorted(int(s) for s in (snaps or {})),
    }
    return senses, cur


# ---------------------------------------------------------------- driver
class AutopilotDriver:
    """Wall-clock sense/actuate loop around an :class:`AutopilotPolicy`.

    ``mode``:

    - ``"observe"`` — scrape + evaluate + log; ZERO ctrl mutations (the
      manager sees only the same read-only scrapes any telemetry client
      sends), so a cluster under an observing autopilot is
      byte-identical to one with no autopilot.
    - ``"act"`` — additionally lower fired decisions onto the ctrl
      plane and announce mode/cooldowns so the servers' autopilot
      gauges export the policy state.

    Test seams: ``sense_fn`` replaces the live scrape, ``ctrl``
    replaces the manager stub (a callable taking a CtrlRequest),
    ``conf_ctl`` / ``proxy_ctl`` carry the actuators whose transports
    live outside the ctrl plane (client ConfChange, proxy supervisor).
    """

    def __init__(
        self,
        manager_addr: Optional[Tuple[str, int]],
        policy: AutopilotPolicy,
        mode: str = "observe",
        scrape_s: float = 1.0,
        timeout: float = 8.0,
        ctrl: Optional[Callable[[CtrlRequest], Any]] = None,
        conf_ctl: Optional[Callable[[List[int]], Any]] = None,
        proxy_ctl: Optional[Callable[[Any], Any]] = None,
        sense_fn: Optional[Callable[[], Optional[dict]]] = None,
        slo_policy: Optional[Any] = None,
    ):
        if mode not in ("observe", "act"):
            raise ValueError(f"unknown autopilot mode {mode!r}")
        self.manager_addr = manager_addr
        self.policy = policy
        self.mode = mode
        self.scrape_s = float(scrape_s)
        self.timeout = float(timeout)
        self._ctrl = ctrl
        self.conf_ctl = conf_ctl
        self.proxy_ctl = proxy_ctl
        self._sense_fn = sense_fn
        # graftwatch attachment (host/graftwatch.py SloPolicy): when
        # given, each scrape also pulls the manager's fleet series
        # (watch_series — a read-only request, so observe mode stays
        # mutation-free), folds any NEW windows through the policy, and
        # feeds the latched burn verdicts as senses["slo_burn"]
        self.slo_policy = slo_policy
        self._slo_widx = -1
        self._prev: Optional[dict] = None
        self._stub = None
        #: rendered ctrl mutations actually SENT (empty in observe mode
        #: by construction — the gate's byte-identical check)
        self.actuation_log: List[str] = []
        #: every fired decision, rendered (observe mode logs here too)
        self.decision_log: List[str] = []

    # ------------------------------------------------------------ ctrl
    def _request(self, req: CtrlRequest) -> Any:
        if self._ctrl is not None:
            return self._ctrl(req)
        from ..client.endpoint import ClientCtrlStub

        try:
            if self._stub is None:
                self._stub = ClientCtrlStub(self.manager_addr)
            return self._stub.request(req, timeout=self.timeout)
        except Exception as e:
            pf_warn(logger, f"ctrl request {req.kind} failed: {e}")
            try:
                if self._stub is not None:
                    self._stub.sock.close()
            except Exception:
                pass
            self._stub = None
            return None

    def close(self) -> None:
        if self._stub is not None:
            try:
                self._stub.close()
            except Exception:
                pass
            self._stub = None

    # ---------------------------------------------------------- senses
    def _scrape(self) -> Optional[dict]:
        from ..client.endpoint import scrape_metrics

        info = self._request(CtrlRequest("query_info"))
        if info is None:
            return None
        snaps = scrape_metrics(self.manager_addr, timeout=self.timeout)
        senses, self._prev = build_senses(snaps, info, self._prev)
        if self.slo_policy is not None:
            burn = self._scrape_burn()
            if burn:
                senses["slo_burn"] = burn
        return senses

    def _scrape_burn(self) -> Optional[dict]:
        """Pull the fleet series and fold NEW windows (widx strictly
        beyond the last observed one) through the attached SloPolicy;
        return its latched status.  Windows still in flight next scrape
        are re-merged then — only completed indices are consumed, so
        one window is never double-counted."""
        from .graftwatch import windows

        rep = self._request(CtrlRequest("watch_series"))
        export = (getattr(rep, "payloads", None) or {}).get("fleet") \
            if rep is not None else None
        if not export:
            return None
        fresh = [
            w for w in windows(export) if w["widx"] > self._slo_widx
        ]
        # the newest widx may still be accumulating frames; hold it
        # back one scrape so a partial window can't fake a burn dip
        if fresh:
            fresh = fresh[:-1]
        for w in fresh:
            self.slo_policy.observe_window(w)
            self._slo_widx = w["widx"]
        return self.slo_policy.status() or None

    # ------------------------------------------------------------ loop
    def step(self) -> List[Decision]:
        """One sense->decide(->actuate) round."""
        senses = (
            self._sense_fn() if self._sense_fn is not None
            else self._scrape()
        )
        if senses is None:
            return []
        decisions = self.policy.evaluate(senses)
        for d in decisions:
            self.decision_log.append(d.render())
            pf_info(logger, f"decision: {d.render()}")
        if self.mode == "act":
            for d in decisions:
                self._actuate(d)
            self._announce()
        return decisions

    def play(self, stop: threading.Event) -> None:
        """Run rounds on the scrape cadence until ``stop`` is set."""
        while not stop.is_set():
            try:
                self.step()
            except Exception as e:  # a flaky scrape must not kill the loop
                pf_warn(logger, f"autopilot round failed: {e}")
            stop.wait(self.scrape_s)
        self.close()

    # -------------------------------------------------------- actuate
    def _send(self, what: str, req: CtrlRequest) -> None:
        self.actuation_log.append(what)
        rep = self._request(req)
        if rep is None:
            pf_warn(logger, f"actuation got no reply: {what}")

    def _actuate(self, d: Decision) -> None:
        if d.actuator == "lead_move":
            self._send(
                f"autopilot_ctl demote -> s{d.target} [{d.reason}]",
                CtrlRequest(
                    "autopilot_ctl", servers=[int(d.target)],
                    payload={"act": "demote", "reason": d.reason},
                ),
            )
        elif d.actuator == "batch":
            self._send(
                f"autopilot_ctl retune api_max_batch={d.arg}",
                CtrlRequest(
                    "autopilot_ctl",
                    payload={"act": "retune",
                             "api_max_batch": int(d.arg)},
                ),
            )
        elif d.actuator == "pipeline":
            self._send(
                f"autopilot_ctl retune pipeline={bool(d.arg)}",
                CtrlRequest(
                    "autopilot_ctl",
                    payload={"act": "retune",
                             "pipeline": bool(d.arg)},
                ),
            )
        elif d.actuator == "conf_resize":
            if self.conf_ctl is None:
                pf_warn(logger, "conf_resize fired with no conf_ctl "
                                "hook; dropped")
                return
            self.actuation_log.append(
                f"conf_ctl responders={list(d.arg)}"
            )
            self.conf_ctl(list(d.arg))
        elif d.actuator == "reshard":
            self._send(
                f"range_change {d.arg.get('op')} "
                f"[{d.arg.get('start')!r},{d.arg.get('end')!r}) "
                f"-> g{d.arg.get('dst_group')}",
                CtrlRequest("range_change", payload=dict(d.arg)),
            )
        elif d.actuator == "recommend":
            pf_info(logger, f"recommend (compile-time): {d.arg}")

    def _announce(self) -> None:
        """Export the policy state through the servers' gauges (act
        mode only — observe mode must stay mutation-free)."""
        self._request(CtrlRequest("autopilot_ctl", payload={
            "act": "announce", "mode": self.mode,
            "cooldowns": self.policy.cooldowns(),
        }))
