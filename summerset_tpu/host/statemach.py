"""StateMachine: the in-memory KV applier behind exec/ack queues.

Parity: reference ``src/server/statemach.rs`` — ``Command::{Get, Put}`` ->
``CommandResult::{Get{value}, Put{old_value}}`` applied by an executor task
owning a ``HashMap`` (statemach.rs:21-72, executor :170-219).  The applier
core is a static function for testability, mirroring the reference's
deliberate pattern (statemach.rs:191-193).
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Any, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Command:
    """Get(key) / Put(key, value) / Scan(key, end, limit) (parity:
    ``Command``; scan is this repo's ordered range-read extension —
    half-open ``[key, end)``, ``end=None`` unbounded, ``limit=0``
    unlimited).  The scan fields default so decoders that only fill the
    get/put triple (utils/wirecodec fast paths) resolve them through the
    class attributes."""

    kind: str  # "get" | "put" | "scan"
    key: str
    value: Optional[str] = None
    end: Optional[str] = None   # scan: exclusive upper bound
    limit: int = 0              # scan: max keys returned (0 = no cap)


@dataclasses.dataclass(frozen=True)
class CommandResult:
    """Get -> value, Put -> old_value, Scan -> items (sorted
    ``(key, value)`` pairs) (parity: ``CommandResult``)."""

    kind: str
    value: Optional[str] = None
    old_value: Optional[str] = None
    items: Optional[tuple] = None  # scan: ((key, value), ...) sorted


def apply_command(kv: Dict[str, str], cmd: Command) -> CommandResult:
    """Pure applier core (parity: the static ``execute`` fn)."""
    if cmd.kind == "get":
        return CommandResult("get", value=kv.get(cmd.key))
    if cmd.kind == "put":
        old = kv.get(cmd.key)
        kv[cmd.key] = cmd.value if cmd.value is not None else ""
        return CommandResult("put", old_value=old)
    if cmd.kind == "scan":
        return CommandResult("scan", items=scan_items(
            kv, cmd.key, cmd.end, cmd.limit,
        ))
    raise ValueError(f"unknown command kind {cmd.kind}")


def scan_items(kv: Dict[str, str], start: str, end: Optional[str],
               limit: int) -> tuple:
    """Ordered range read over a KV dict: sorted ``(key, value)`` pairs
    with ``start <= key`` (``< end`` when bounded), truncated to
    ``limit`` when positive.  One seam shared by the fused applier and
    the learner read tier so both serving paths return byte-identical
    shapes."""
    keys = sorted(
        k for k in kv
        if k >= start and (end is None or k < end)
    )
    if limit and limit > 0:
        keys = keys[:limit]
    return tuple((k, kv[k]) for k in keys)


class StateMachine:
    """Executor-owned KV store with submit/ack queues.

    ``submit_cmd``/``get_result`` mirror the reference hub channels
    (statemach.rs:117-150); ``do_sync_cmd`` is the blocking path used by
    snapshotting (:151).
    """

    def __init__(self):
        self._in: queue.Queue = queue.Queue()
        self._out: queue.Queue = queue.Queue()
        self._kv: Dict[str, str] = {}
        self._thread = threading.Thread(target=self._executor, daemon=True)
        self._thread.start()

    def submit_cmd(self, cmd_id: Any, cmd: Command) -> None:
        self._in.put((cmd_id, cmd))

    def get_result(self, timeout: Optional[float] = None
                   ) -> Tuple[Any, CommandResult]:
        return self._out.get(timeout=timeout)

    def do_sync_cmd(self, cmd: Command) -> CommandResult:
        done: queue.Queue = queue.Queue()
        self._in.put((("__sync__", done), cmd))
        return done.get()

    def snapshot_items(self):
        """Blocking consistent view for snapshot dumps (drains in-order)."""
        done: queue.Queue = queue.Queue()
        self._in.put((("__snap__", done), None))
        return done.get()

    def stop(self) -> None:
        self._in.put(None)
        self._thread.join(timeout=5)

    def _executor(self) -> None:
        while True:
            item = self._in.get()
            if item is None:
                return
            cmd_id, cmd = item
            if isinstance(cmd_id, tuple) and cmd_id[0] == "__snap__":
                cmd_id[1].put(dict(self._kv))
                continue
            res = apply_command(self._kv, cmd)
            if isinstance(cmd_id, tuple) and cmd_id[0] == "__sync__":
                cmd_id[1].put(res)
            else:
                self._out.put((cmd_id, res))
