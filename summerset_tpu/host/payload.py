"""PayloadStore: host-side value-id <-> request-batch storage.

The device kernels commit int32 *references*; actual request batches
(client id, request id, commands — up to 16MB values in the reference)
never touch HBM (SURVEY.md §7 hard part (b)).  This store assigns dense
per-group value ids, resolves them at execution time, and garbage-collects
below the group's snapshot bar.

The id space mirrors the synthetic-load convention used by the kernels'
bench mode (``value_base`` input): ids are positive, 0 is reserved for the
no-op filler (``protocols/common.py`` NULL_VAL).

Store split (codeword plane): this store holds only *full* request
batches.  The RS protocol family keeps erasure-coded shard subsets in the
sibling :class:`~summerset_tpu.host.codeword.CodewordStore`; the server's
``_resolve_payload`` checks here first and falls back to shard
reconstruction, installing the decoded batch via :meth:`install` so the
decode cost is paid once per value.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional


class PayloadStore:
    def __init__(self, num_groups: int = 1):
        self._lock = threading.Lock()
        self._next = [1] * num_groups
        self._data: list[Dict[int, Any]] = [dict() for _ in range(num_groups)]

    def put(self, group: int, batch: Any, stride: int = 1,
            residue: int = 0) -> int:
        """Store a request batch, returning its value id (>= 1).

        ``stride``/``residue`` partition the id space between concurrent
        proposers (one residue class per replica): without this, two
        servers proposing in the same tick mint the same id for
        different batches and the payload exchange silently cross-wires
        them (first-writer-wins at every peer)."""
        with self._lock:
            # _next starts at 1 and only grows, and the residue bump is
            # non-negative, so vid >= 1 always holds (0 stays the no-op)
            vid = self._next[group]
            if stride > 1:
                vid += (residue - vid) % stride
            self._next[group] = vid + 1
            self._data[group][vid] = batch
        return vid

    def get(self, group: int, vid: int) -> Optional[Any]:
        if vid == 0:
            return None  # no-op filler
        with self._lock:
            return self._data[group].get(vid)

    def install(self, group: int, vid: int, batch: Any,
                overwrite: bool = True) -> None:
        """Install a batch under a peer-minted / reconstructed vid,
        keeping the local minting cursor past it (first-writer-wins when
        ``overwrite`` is False, the payload-exchange dedup rule)."""
        with self._lock:
            if overwrite or vid not in self._data[group]:
                self._data[group][vid] = batch
            self._next[group] = max(self._next[group], vid + 1)

    def note_seen(self, group: int, vid: int) -> None:
        """Bump the minting cursor past an externally observed vid
        (shard-only ingests hold no batch to install)."""
        with self._lock:
            self._next[group] = max(self._next[group], vid + 1)

    def gc_below(self, group: int, vid_floor: int) -> int:
        """Drop payloads with id < vid_floor (snapshot GC); returns count."""
        with self._lock:
            drop = [v for v in self._data[group] if v < vid_floor]
            for v in drop:
                del self._data[group][v]
        return len(drop)

    def size(self, group: int) -> int:
        with self._lock:
            return len(self._data[group])
