"""Client library: endpoint, data/control stubs, drivers, and utilities.

Parity: reference ``src/client/`` + ``summerset_client`` toolkit (SURVEY.md
§2.4/§2.6) — ``GenericEndpoint`` (endpoint.rs:17-54), ``ClientApiStub``
(apistub.rs:16-95), ``ClientCtrlStub`` (ctrlstub.rs), the closed/open-loop
drivers, and the bench / tester / repl / mess utility modes.
"""

from .endpoint import ClientApiStub, ClientCtrlStub, GenericEndpoint  # noqa
from .drivers import DriverClosedLoop, DriverOpenLoop  # noqa: F401
