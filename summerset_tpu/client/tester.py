"""ClientTester: the correctness test suite driven through the manager.

Parity: reference ``summerset_client/src/clients/tester.rs`` — the named
tests (tester.rs:20-35) exercised in CI: ``primitive_ops``,
``client_reconnect``, ``non_leader_reset``, ``leader_node_reset``,
``two_nodes_reset``, ``all_nodes_reset``, ``non_leader_pause``,
``leader_node_pause``, ``node_pause_resume``.  Fault injection goes
through the manager control plane (reset = crash-restart, pause/resume —
tester.rs:242-316), i.e. real process control, not mocks.
"""

from __future__ import annotations

import random
import threading
import time
from typing import List, Optional, Tuple

from ..host.messages import CtrlRequest
from ..utils.linearize import (
    record_get, record_put, record_scan, record_shed_put,
)
from ..utils.logging import pf_info, pf_logger
from .drivers import DriverClosedLoop, DriverOpenLoopPaced
from .endpoint import GenericEndpoint

logger = pf_logger("tester")

ALL_TESTS = [
    "primitive_ops",
    "client_reconnect",
    "non_leader_reset",
    "leader_node_reset",
    "two_nodes_reset",
    "all_nodes_reset",
    "non_leader_pause",
    "leader_node_pause",
    "node_pause_resume",
]


# ------------------------------------------------------- nemesis soak plane
def recorded_closed_loop(
    manager_addr: Tuple[str, int],
    ci: int,
    keys: List[str],
    stop: threading.Event,
    ops: list,
    seed: int = 0,
    timeout: float = 3.0,
) -> None:
    """One closed-loop client recording a timed operation history in
    ``utils/linearize`` Op form while faults play (the nemesis soak's
    workload; parity role: the reference tester's checked ops, plus the
    Jepsen-style history recording the TLA+ specs only model).

    Semantics of the record: successes carry [t_inv, t_resp]; a put that
    timed out / disconnected is recorded UNACKED (it may or may not have
    executed — the checker is free to place or drop it); a SHED put is
    recorded as a negative ack (guaranteed never executed — the checker
    excludes it, so a get observing its value is a violation) and the
    client honors the retry-after hint; a redirect is no op at all (the
    server refused without proposing).  Gets that fail observe nothing
    and are not recorded.
    """
    rng = random.Random(seed * 1009 + ci)
    try:
        ep = GenericEndpoint(manager_addr)
        ep.connect()
    except Exception:
        return  # cluster unreachable at spawn: nothing observed
    drv = DriverClosedLoop(ep, timeout=timeout)
    seq = 0
    while not stop.is_set():
        key = keys[seq % len(keys)]
        t0 = time.monotonic()
        if rng.random() < 0.5:
            val = f"c{ci}-{seq}"
            rep = drv.put(key, val)
            t1 = time.monotonic()
            if rep.kind == "success":
                ops.append(record_put(ci, key, val, t0, t1, True))
            elif rep.kind == "shed":
                ops.append(record_shed_put(ci, key, val, t0, t1))
                drv.backoff.sleep_hint(rep.retry_after)
            elif rep.kind in ("timeout", "failure", "disconnect"):
                ops.append(record_put(ci, key, val, t0, None, False))
                drv._failover(rep)
        else:
            rep = drv.get(key)
            t1 = time.monotonic()
            if rep.kind == "success":
                val = rep.result.value if rep.result else None
                ops.append(record_get(ci, key, val, t0, t1))
            elif rep.kind == "shed":
                drv.backoff.sleep_hint(rep.retry_after)
            elif rep.kind in ("timeout", "failure", "disconnect"):
                drv._failover(rep)
        seq += 1
    try:
        ep.leave()
    except Exception:
        pass


def start_recorded_clients(
    manager_addr: Tuple[str, int],
    num_clients: int,
    keys: List[str],
    stop: threading.Event,
    ops: list,
    seed: int = 0,
    timeout: float = 3.0,
) -> List[threading.Thread]:
    """Spawn ``num_clients`` recorder threads (list.append is atomic, so
    they share one ``ops`` list).  Join them after setting ``stop``."""
    threads = [
        threading.Thread(
            target=recorded_closed_loop,
            args=(manager_addr, ci, keys, stop, ops, seed, timeout),
            daemon=True,
        )
        for ci in range(num_clients)
    ]
    for t in threads:
        t.start()
    return threads


# ---------------------------------------------------- workload soak plane
def recorded_open_loop(
    manager_addr: Tuple[str, int],
    ci: int,
    stream,
    rate_of,
    stop: threading.Event,
    ops: list,
    stats: list,
    seed: int = 0,
    timeout: float = 5.0,
) -> None:
    """One OPEN-LOOP client paced by a WorkloadPlan stream: arrivals
    come at ``rate_of()`` reqs/s (the runner's closure over the plan's
    phase table — rate 0 stops issuing, e.g. past the horizon)
    regardless of outstanding replies, with seeded-expovariate
    inter-arrival jitter.  Op kinds/keys/values come from ``stream``
    (``WorkloadPlan.opstream(ci)`` — a pure function of the seed).

    Records the same ``utils/linearize`` history as the closed-loop
    recorder, extended with the overload outcomes: acked ops carry
    [t_inv, t_resp] (their latency IS t_resp - t_inv, which the soak's
    accepted-op p99 reads straight off the history); shed puts are
    recorded as negative acks; arrivals landing inside a shed
    retry-after gate are counted ``held`` and dropped client-side (the
    client half of graceful degradation); expiries record unacked puts.
    Per-client driver counters land in ``stats`` at exit.
    """
    rng = random.Random(seed * 4241 + ci * 97 + 1)
    try:
        ep = GenericEndpoint(manager_addr)
        ep.connect()
    except Exception:
        return  # cluster unreachable at spawn: nothing observed
    drv = DriverOpenLoopPaced(ep, timeout=timeout, seed=seed * 31 + ci)
    # scans are bounded just past the plan's own keyspace: the harness
    # writes bookkeeping keys (warm/calibration/recovery) whose puts the
    # recorded history does not carry, and an unbounded scan straying
    # into them would observe values the checker must call phantom
    plan = getattr(stream, "plan", None)
    if plan is not None and getattr(plan, "trace", None):
        scan_hi = max(k for _, k, _ in plan.trace) + "\x00"
    elif getattr(stream, "keys", None):
        scan_hi = max(stream.keys) + "\x00"
    else:
        scan_hi = None

    def record(info: dict, rep) -> None:
        t1 = time.monotonic()
        if rep.kind == "success":
            if info["kind"] == "put":
                ops.append(record_put(
                    ci, info["key"], info["value"], info["t0"],
                    info["t0"] + rep.latency, True,
                ))
            elif info["kind"] == "scan":
                # acked range read: the observed (key, value) cut joins
                # the history as a multi-key read; a limit-capped result
                # proves absence only up to its last returned key.
                # Shed/timed-out scans observe nothing — not recorded.
                items = (rep.result.items or ()) if rep.result else ()
                limit = int(info.get("limit") or 0)
                ops.append(record_scan(
                    ci, info["key"], info.get("end"), items, info["t0"],
                    info["t0"] + rep.latency,
                    truncated=bool(limit and len(items) >= limit),
                ))
            else:
                val = rep.result.value if rep.result else None
                ops.append(record_get(
                    ci, info["key"], val, info["t0"],
                    info["t0"] + rep.latency,
                ))
        elif rep.kind == "shed" and info["kind"] == "put":
            ops.append(record_shed_put(
                ci, info["key"], info["value"], info["t0"], t1,
            ))
        elif rep.kind == "failure" and info["kind"] == "put":
            # an explicit error reply: conservatively unacked (the
            # reference error path replies without proposing, but the
            # checker need not trust that)
            ops.append(record_put(
                ci, info["key"], info["value"], info["t0"], None, False,
            ))

    def expire() -> None:
        for info in drv.expired():
            if info["kind"] == "put":
                ops.append(record_put(
                    ci, info["key"], info["value"], info["t0"], None,
                    False,
                ))

    t_next = time.monotonic()
    while not stop.is_set():
        now = time.monotonic()
        expire()
        rate = float(rate_of())
        if rate > 0 and now >= t_next:
            if drv.gated(now):
                drv.counts["held"] += 1
            else:
                kind, key, size = stream.next()
                val = None
                if kind == "put":
                    body = f"c{ci}-{drv.next_req}"
                    val = body + "x" * max(0, size - len(body))
                elif kind == "scan":
                    val = size  # scan length rides value -> limit cap
                drv.issue(kind, key, val,
                          end=scan_hi if kind == "scan" else None)
            t_next = now + rng.expovariate(rate)
        budget = (
            min(max(t_next - now, 0.0005), 0.02) if rate > 0 else 0.02
        )
        for info, rep in drv.poll(budget):
            record(info, rep)
    # drain stragglers briefly, then expire what never answered
    t_end = time.monotonic() + min(timeout, 2.0)
    while drv.inflight and time.monotonic() < t_end:
        for info, rep in drv.poll(0.1):
            record(info, rep)
    for info in drv.inflight.values():
        if info["kind"] == "put":
            ops.append(record_put(
                ci, info["key"], info["value"], info["t0"], None, False,
            ))
    drv.inflight.clear()
    stats.append({"ci": ci, **drv.counts})
    try:
        ep.leave()
    except Exception:
        pass


def start_workload_clients(
    manager_addr: Tuple[str, int],
    plan,
    rate_total_of,
    stop: threading.Event,
    ops: list,
    stats: list,
    timeout: float = 5.0,
) -> List[threading.Thread]:
    """Spawn ``plan.clients`` open-loop recorder threads, each driving
    its own ``plan.opstream(ci)`` at an equal share of the total
    offered rate ``rate_total_of()`` (reqs/s)."""
    n = max(1, int(plan.clients))

    def rate_of():
        return float(rate_total_of()) / n

    threads = [
        threading.Thread(
            target=recorded_open_loop,
            args=(manager_addr, ci, plan.opstream(ci), rate_of, stop,
                  ops, stats, plan.seed, timeout),
            daemon=True,
        )
        for ci in range(n)
    ]
    for t in threads:
        t.start()
    return threads


class ClientTester:
    def __init__(self, manager_addr: Tuple[str, int],
                 settle: float = 2.0):
        self.manager_addr = manager_addr
        self.settle = settle

    # ------------------------------------------------------------ helpers
    def _fresh(self) -> Tuple[GenericEndpoint, DriverClosedLoop]:
        ep = GenericEndpoint(self.manager_addr)
        ep.connect()
        # generous per-request timeout: the reset cases recover through
        # WAL replay + mesh rejoin, which stretches well past the default
        # on slow/loaded boxes (checked_* retries spin fast on redirects,
        # so only genuinely dead windows pay this budget)
        return ep, DriverClosedLoop(ep, timeout=8.0)

    def _leader(self, ep: GenericEndpoint) -> Optional[int]:
        info = ep.ctrl.request(CtrlRequest("query_info"))
        return info.leader

    def _reset(self, ep, servers: Optional[List[int]], durable=True):
        ep.ctrl.request(
            CtrlRequest("reset_servers", servers=servers, durable=durable),
            timeout=60,
        )
        time.sleep(self.settle)

    def _pause(self, ep, servers: Optional[List[int]]):
        ep.ctrl.request(CtrlRequest("pause_servers", servers=servers),
                        timeout=60)
        time.sleep(self.settle)

    def _resume(self, ep, servers: Optional[List[int]]):
        ep.ctrl.request(CtrlRequest("resume_servers", servers=servers),
                        timeout=60)
        time.sleep(self.settle)

    # -------------------------------------------------------------- tests
    def primitive_ops(self):
        ep, drv = self._fresh()
        drv.checked_get("job", expect=None)
        drv.checked_put("job", "kv_store")
        drv.checked_get("job", expect="kv_store")
        drv.checked_put("job", "kv_store_2")
        drv.checked_get("job", expect="kv_store_2")
        ep.leave()

    def client_reconnect(self):
        ep, drv = self._fresh()
        drv.checked_put("job", "kv_store")
        ep.leave(keep_ctrl=False)
        ep2, drv2 = self._fresh()
        drv2.checked_get("job", expect="kv_store")
        ep2.leave()

    def non_leader_reset(self):
        ep, drv = self._fresh()
        drv.checked_put("job", "kv_store")
        leader = self._leader(ep) or 0
        victim = next(
            s for s in sorted(ep.servers) if s != leader
        )
        self._reset(ep, [victim])
        ep2, drv2 = self._fresh()
        drv2.checked_get("job", expect="kv_store")
        ep2.leave()
        ep.leave()

    def leader_node_reset(self):
        ep, drv = self._fresh()
        drv.checked_put("job", "kv_store")
        leader = self._leader(ep)
        if leader is None:
            leader = ep.current
        self._reset(ep, [leader])
        ep2, drv2 = self._fresh()
        drv2.checked_get("job", expect="kv_store")
        ep2.leave()
        ep.leave()

    def two_nodes_reset(self):
        ep, drv = self._fresh()
        drv.checked_put("job", "kv_store")
        leader = self._leader(ep) or 0
        others = [s for s in sorted(ep.servers) if s != leader]
        self._reset(ep, others[:1] + [leader])
        ep2, drv2 = self._fresh()
        drv2.checked_get("job", expect="kv_store")
        ep2.leave()
        ep.leave()

    def all_nodes_reset(self):
        ep, drv = self._fresh()
        drv.checked_put("job", "kv_store")
        self._reset(ep, None)
        ep2, drv2 = self._fresh()
        drv2.checked_get("job", expect="kv_store")
        ep2.leave()
        ep.leave()

    def non_leader_pause(self):
        ep, drv = self._fresh()
        drv.checked_put("job", "kv_store")
        leader = self._leader(ep) or 0
        victim = next(s for s in sorted(ep.servers) if s != leader)
        self._pause(ep, [victim])
        drv.checked_put("job", "kv_store_2")
        drv.checked_get("job", expect="kv_store_2")
        self._resume(ep, [victim])
        ep.leave()

    def leader_node_pause(self):
        ep, drv = self._fresh()
        drv.checked_put("job", "kv_store")
        leader = self._leader(ep)
        if leader is None:
            leader = ep.current
        self._pause(ep, [leader])
        ep2, drv2 = self._fresh()
        drv2.checked_get("job", expect="kv_store")
        ep2.leave()
        self._resume(ep, [leader])
        ep.leave()

    def node_pause_resume(self):
        """Pause the *current leader* (whoever inherited leadership from
        earlier churn), write through the survivors, resume, write again.
        The victim is the queried leader — not a fixed id — and the client
        rotates off it on timeout (tester.rs:429-433 reconnects around
        every fault for the same reason)."""
        ep, drv = self._fresh()
        drv.checked_put("job", "kv_store")
        victim = self._leader(ep)
        if victim is None:
            victim = sorted(ep.servers)[-1]
        self._pause(ep, [victim])
        ep.rotate(avoid=victim)
        drv.checked_put("job", "kv_store_2")
        self._resume(ep, [victim])
        drv.checked_put("job", "kv_store_3")
        drv.checked_get("job", expect="kv_store_3")
        ep.leave()

    # ------------------------------------------------------------- runner
    def run_tests(self, names: Optional[List[str]] = None) -> dict:
        results = {}
        for name in names or ALL_TESTS:
            fn = getattr(self, name)
            try:
                fn()
                results[name] = "PASS"
                pf_info(logger, f"test {name}: PASS")
            except Exception as e:
                results[name] = f"FAIL: {e}"
                pf_info(logger, f"test {name}: FAIL ({e})")
        return results
