"""Client endpoint + stubs.

Parity: ``ClientApiStub`` sends the client id as its first frame and then
exchanges ``ApiRequest``/``ApiReply`` (apistub.rs:16-95); ``ClientCtrlStub``
receives its assigned id on connect and exchanges ``CtrlRequest``/
``CtrlReply`` (ctrlstub.rs); ``GenericEndpoint`` composes both and handles
server (re)selection including leader redirects (endpoint.rs:17-54).
"""

from __future__ import annotations

import socket
from typing import Optional, Tuple

from ..host.messages import ApiReply, ApiRequest, CtrlReply, CtrlRequest
from ..host.statemach import Command
from ..utils import safetcp
from ..utils.errors import SummersetError


def _ctrl_scrape(manager_addr: Tuple[str, int], req: "CtrlRequest",
                 timeout: float) -> Optional[dict]:
    """Best-effort one-shot gather through the manager ctrl plane: send
    ``req``, return ``{server id (str): payload}`` sorted by id, or
    ``None`` when the manager is unreachable/mid-fault — the shared
    plumbing under every ``*_dump`` scrape helper."""
    try:
        stub = ClientCtrlStub(manager_addr)
        try:
            rep = stub.request(req, timeout=timeout)
        finally:
            stub.close()
    except Exception:
        return None
    return {
        str(sid): payload
        for sid, payload in sorted((rep.payloads or {}).items())
    }


def scrape_metrics(manager_addr: Tuple[str, int],
                   timeout: float = 30.0, compact: bool = False) -> dict:
    """One-shot telemetry scrape: ``metrics_dump`` through the manager,
    returning ``{server id (str): snapshot}`` — the JSON-able per-server
    combination of device metric lanes, host registry histograms, and
    sampled slot traces (``server.metrics_snapshot``).  ``compact=True``
    trims each snapshot to the device lane totals plus the headline
    histograms (for artifacts committing many runs, e.g. the soak
    matrix).  Best-effort: an unreachable manager or mid-fault cluster
    yields ``{}`` rather than failing the caller's bench/soak run."""
    # only the NETWORK half is best-effort: a snapshot-schema mismatch in
    # the trimming below must raise loudly, not silently commit
    # server_metrics: {} into bench artifacts while CI stays green
    out = _ctrl_scrape(
        manager_addr, CtrlRequest("metrics_dump"), timeout
    )
    if out is None:
        return {}
    if compact:
        keep = ("ticks_to_commit", "api_request_latency_us",
                "wal_fsync_us", "wal_group_commit_batch")
        out = {
            sid: {
                "tick": snap["tick"],
                "device_lanes": snap["device"]["lanes"],
                "histograms": {
                    k: v
                    for k, v in snap["host"]["histograms"].items()
                    if k.split("{", 1)[0] in keep
                },
            }
            for sid, snap in out.items()
        }
    return out


def scrape_flight(manager_addr: Tuple[str, int],
                  last_n: Optional[int] = None,
                  timeout: float = 30.0) -> dict:
    """One-shot graftscope scrape: ``flight_dump`` through the manager,
    returning ``{server id (str): flight dump}`` — each replica's typed
    event ring (``server.flight_snapshot``), trimmed to the ``last_n``
    newest events per replica when given.  Best-effort like
    :func:`scrape_metrics`: an unreachable manager yields ``{}`` so a
    failing soak's bundle writer never dies on its own diagnostics."""
    out = _ctrl_scrape(
        manager_addr,
        CtrlRequest(
            "flight_dump",
            payload=(
                {"last_n": int(last_n)}
                if last_n is not None else None
            ),
        ),
        timeout,
    )
    return {} if out is None else out


def scrape_fleet(manager_addr: Tuple[str, int],
                 timeout: float = 15.0) -> Optional[dict]:
    """One-shot graftwatch scrape: ``watch_series`` through the manager,
    returning the FleetSeries export (``{"v", "retain", "series": [...]}``)
    or ``None`` when the manager is unreachable.  Answered from the
    manager's own ring — no server fan-out, so it stays cheap enough
    for a dashboard to poll every second (``scripts/fleet_top.py``)."""
    out = _ctrl_scrape(
        manager_addr, CtrlRequest("watch_series"), timeout
    )
    if out is None:
        return None
    return out.get("fleet")


class ClientCtrlStub:
    def __init__(self, manager_addr: Tuple[str, int]):
        self.sock = socket.create_connection(manager_addr, timeout=15)
        self.sock.settimeout(None)
        self.id: int = int(safetcp.recv_msg_sync(self.sock))

    def request(self, req: CtrlRequest, timeout: float = 30.0) -> CtrlReply:
        self.sock.settimeout(timeout)
        try:
            safetcp.send_msg_sync(self.sock, req)
            return safetcp.recv_msg_sync(self.sock)
        finally:
            self.sock.settimeout(None)

    def close(self) -> None:
        try:
            safetcp.send_msg_sync(self.sock, CtrlRequest("leave"))
            safetcp.recv_msg_sync(self.sock)
        except Exception:
            pass
        self.sock.close()


class ClientApiStub:
    """Data-plane stub.  ``codec=None`` follows the process-wide wire
    codec default (utils/wirecodec.py): hot requests leave in the
    compact binary form; the reply side dispatches per frame, so the
    stub talks to codec-on and codec-off servers alike."""

    def __init__(self, client_id: int, api_addr: Tuple[str, int],
                 connect_timeout: float = 15.0,
                 codec: Optional[bool] = None):
        self.codec = codec
        self.sock = socket.create_connection(
            tuple(api_addr), timeout=max(connect_timeout, 0.05)
        )
        self.sock.settimeout(None)
        safetcp.send_msg_sync(self.sock, client_id)

    def send_req(self, req: ApiRequest) -> None:
        safetcp.send_msg_sync(self.sock, req, codec=self.codec)

    def recv_reply(self, timeout: Optional[float] = None) -> ApiReply:
        self.sock.settimeout(timeout)
        try:
            return safetcp.recv_msg_sync(self.sock)
        finally:
            self.sock.settimeout(None)

    def close(self) -> None:
        self.sock.close()


class GenericEndpoint:
    """Manager-guided endpoint with redirect-aware server selection.

    Proxy discovery (serving-plane split, ``host/ingress.py``): when the
    manager's ``query_info`` lists registered ingress proxies, the
    endpoint connects to a proxy instead of a replica — same wire, same
    redirect/backoff machinery, the proxy tier just absorbs the leader
    topology.  ``via_proxy="auto"`` (the default) uses proxies exactly
    when some are registered AND the caller did not pin a ``server_id``
    — so every existing direct-to-replica deployment, test, and soak is
    byte-identical (no proxies registered -> nothing changes).
    ``rotate``/``reconnect`` walk the proxy set in proxy mode (a crashed
    proxy deregisters with its ctrl connection, so the refresh inside
    ``rotate`` IS the rediscovery), and fall back to direct replica
    connections if the whole proxy tier is gone.
    """

    def __init__(self, manager_addr: Tuple[str, int],
                 server_id: Optional[int] = None,
                 via_proxy="auto", wire_codec: Optional[bool] = None):
        self.ctrl = ClientCtrlStub(manager_addr)
        self.id = self.ctrl.id
        self.prefer = server_id
        self.via_proxy = via_proxy
        # wire codec pin for the data-plane stub (None = process
        # default); the ctrl stub stays pickle — ctrl kinds are cold
        self.wire_codec = wire_codec
        self.api: Optional[ClientApiStub] = None
        self.servers = {}
        self.proxies = {}
        self.proxy_mode = False
        self.current: Optional[int] = None
        # leader-redirect cache: the freshest leader hint this client has
        # observed (from redirect replies via ``note_leader`` or manager
        # query_info).  Under fault schedules the manager's view can lag
        # a whole election behind the servers', so the data-plane hint
        # takes precedence when picking a failover target.
        self.leader_cache: Optional[int] = None

    def note_leader(self, sid: Optional[int]) -> None:
        """Record a data-plane leader hint (drivers call this on every
        redirect reply carrying one)."""
        if sid is not None and sid >= 0:
            self.leader_cache = sid

    def _refresh_info(self, info) -> None:
        if info.servers:
            self.servers = info.servers
        self.proxies = dict(getattr(info, "proxies", None) or {})
        if info.leader is not None:
            self.leader_cache = info.leader
        self.proxy_mode = bool(self.proxies) and (
            self.via_proxy is True
            or (self.via_proxy == "auto" and self.prefer is None)
        )

    def connect(self, timeout: Optional[float] = None) -> None:
        """``timeout`` bounds the server CONNECT only; the manager query
        keeps the ctrl stub's own budget (shrinking it risks stranding a
        stale reply in the ctrl stream — see ``rotate``)."""
        info = self.ctrl.request(CtrlRequest("query_info"))
        self._refresh_info(info)
        if self.proxy_mode:
            # spread clients across the proxy tier by client id (stable
            # per client, balanced across the fleet)
            cands = sorted(self.proxies)
            self._connect_to(
                cands[self.id % len(cands)], timeout=timeout
            )
            return
        if not info.servers:
            raise SummersetError("no servers joined yet")
        target = self.prefer
        if target is None or target not in info.servers:
            target = (
                info.leader
                if info.leader is not None and info.leader in info.servers
                else sorted(info.servers)[0]
            )
        self._connect_to(target, timeout=timeout)

    def _connect_to(self, sid: int,
                    timeout: Optional[float] = None) -> None:
        if self.api is not None:
            self.api.close()
            self.api = None
        if self.proxy_mode:
            api_addr = self.proxies[sid]
        else:
            api_addr, _ = self.servers[sid]
        self.api = ClientApiStub(
            self.id, api_addr,
            connect_timeout=15.0 if timeout is None else timeout,
            codec=self.wire_codec,
        )
        self.current = sid

    def reconnect(self, sid: Optional[int] = None,
                  timeout: Optional[float] = None) -> None:
        pool = self.proxies if self.proxy_mode else self.servers
        if sid is not None and sid in pool:
            self._connect_to(sid, timeout=timeout)
        else:
            # unknown/stale sid: fall back to a fresh manager-guided
            # connect, still honoring the caller's connect budget (a
            # hinted-but-departed server must not stall the request past
            # its deadline)
            self.connect(timeout=timeout)

    def rotate(self, avoid: Optional[int] = None,
               deadline: Optional[float] = None) -> None:
        """Fail over to a different server after a timeout.

        Parity: the reference tester leaves + reconnects around faults
        (tester.rs:429-433) and the endpoint re-queries the manager
        (endpoint.rs:17-54).  Prefers the freshest leader hint — the
        data-plane redirect cache first, then the manager's view — unless
        that is the server being avoided (e.g. it just got paused and the
        manager has not seen the new leader yet), else round-robins to the
        next id so repeated timeouts walk the whole membership.

        ``deadline`` (monotonic seconds) bounds the whole walk: each
        connect attempt gets at most the remaining budget, so a caller's
        timeout is honored even when several candidates are black holes.
        """
        import time

        def budget() -> Optional[float]:
            if deadline is None:
                return None
            return deadline - time.monotonic()

        leader = None
        b = budget()
        # the ctrl query keeps its FIXED 5s timeout: shrinking it below
        # what the manager needs under load would strand a stale reply
        # in the ctrl stream (consumed by the NEXT request — a desync
        # worse than a late rotate).  When the caller's budget is nearly
        # gone, skip the query and walk the cached membership instead —
        # the deadline bounding belongs on the connect attempts below.
        if b is None or b >= 1.0:
            try:
                info = self.ctrl.request(
                    CtrlRequest("query_info"), timeout=5
                )
                self._refresh_info(info)
                leader = info.leader
            except Exception:
                pass
        if avoid is None:
            avoid = self.current
        if self.proxy_mode:
            # proxy tier: round-robin the registered proxies (the query
            # above already dropped any crashed proxy — its ctrl
            # connection death IS the deregistration); leader hints are
            # server-space and do not apply here
            order = self._walk_order(sorted(self.proxies), avoid, ())
        else:
            if not self.servers:
                return
            order = self._walk_order(
                sorted(self.servers), avoid,
                (self.leader_cache, leader),
            )
        for cand in order:
            b = budget()
            if b is not None and b <= 0:
                return
            try:
                self._connect_to(cand, timeout=b)
                return
            except OSError:
                continue

    @staticmethod
    def _walk_order(cands, avoid, hints):
        """The one failover walk both tiers share: usable ``hints``
        first, then round-robin from ``avoid``, with ``avoid`` itself
        as the last resort (everything else unreachable)."""
        order = []
        for hint in hints:
            if (
                hint is not None and hint in cands
                and hint != avoid and hint not in order
            ):
                order.append(hint)
        start = cands.index(avoid) if avoid in cands else -1
        for off in range(1, len(cands) + 1):
            cand = cands[(start + off) % len(cands)]
            if cand != avoid and cand not in order:
                order.append(cand)
        if avoid in cands:
            order.append(avoid)
        return order

    def follow_redirect(self, hint: Optional[int],
                        deadline: Optional[float] = None) -> None:
        """The one redirect-failover policy every driver shares: note
        the data-plane leader hint, reconnect toward it when it is a
        usable different server, else walk the membership — all bounded
        by ``deadline`` (monotonic seconds) and swallowing connect
        errors (a black-holed hinted server costs this call its budget,
        never an exception; the caller's next retry rotates)."""
        import time

        self.note_leader(hint)
        budget = (
            None if deadline is None else deadline - time.monotonic()
        )
        try:
            if budget is not None and budget <= 0:
                return  # out of budget: the caller's retry rotates
            if hint is not None and hint >= 0 and hint != self.current:
                self.reconnect(hint, timeout=budget)
            else:
                # no hint, or the server pointed at itself (leadership
                # unsettled): walk the membership
                self.rotate(deadline=deadline)
        except Exception:
            pass  # hinted server down: the next retry rotates

    def send_req(self, req_id: int, cmd: Command) -> None:
        assert self.api is not None, "connect() first"
        self.api.send_req(ApiRequest("req", req_id=req_id, cmd=cmd))

    def send_scan(self, req_id: int, start: str, end: Optional[str],
                  limit: int = 0) -> None:
        """Issue an ordered range read over ``[start, end)`` (``end``
        None = unbounded, ``limit`` 0 = no cap).  Rides the "req" kind —
        servers and proxies also accept a bare "scan" ApiRequest kind,
        but the Command form keeps one wire shape for every data op."""
        self.send_req(req_id, Command(
            "scan", start, end=end, limit=int(limit),
        ))

    def send_conf(self, req_id: int, conf_delta: dict) -> None:
        """Issue a ConfChange (parity: ApiRequest::Conf,
        external.rs:106-121)."""
        assert self.api is not None, "connect() first"
        self.api.send_req(
            ApiRequest("conf", req_id=req_id, conf_delta=conf_delta)
        )

    def recv_reply(self, timeout: Optional[float] = None) -> ApiReply:
        assert self.api is not None
        return self.api.recv_reply(timeout=timeout)

    def leave(self, keep_ctrl: bool = False) -> None:
        if self.api is not None:
            try:
                self.api.send_req(ApiRequest("leave"))
                self.api.recv_reply(timeout=2)
            except Exception:
                pass
            self.api.close()
            self.api = None
        if not keep_ctrl:
            self.ctrl.close()
