"""Client endpoint + stubs.

Parity: ``ClientApiStub`` sends the client id as its first frame and then
exchanges ``ApiRequest``/``ApiReply`` (apistub.rs:16-95); ``ClientCtrlStub``
receives its assigned id on connect and exchanges ``CtrlRequest``/
``CtrlReply`` (ctrlstub.rs); ``GenericEndpoint`` composes both and handles
server (re)selection including leader redirects (endpoint.rs:17-54).
"""

from __future__ import annotations

import socket
from typing import Optional, Tuple

from ..host.messages import ApiReply, ApiRequest, CtrlReply, CtrlRequest
from ..host.statemach import Command
from ..utils import safetcp
from ..utils.errors import SummersetError


class ClientCtrlStub:
    def __init__(self, manager_addr: Tuple[str, int]):
        self.sock = socket.create_connection(manager_addr, timeout=15)
        self.sock.settimeout(None)
        self.id: int = int(safetcp.recv_msg_sync(self.sock))

    def request(self, req: CtrlRequest, timeout: float = 30.0) -> CtrlReply:
        self.sock.settimeout(timeout)
        try:
            safetcp.send_msg_sync(self.sock, req)
            return safetcp.recv_msg_sync(self.sock)
        finally:
            self.sock.settimeout(None)

    def close(self) -> None:
        try:
            safetcp.send_msg_sync(self.sock, CtrlRequest("leave"))
            safetcp.recv_msg_sync(self.sock)
        except Exception:
            pass
        self.sock.close()


class ClientApiStub:
    def __init__(self, client_id: int, api_addr: Tuple[str, int]):
        self.sock = socket.create_connection(tuple(api_addr), timeout=15)
        self.sock.settimeout(None)
        safetcp.send_msg_sync(self.sock, client_id)

    def send_req(self, req: ApiRequest) -> None:
        safetcp.send_msg_sync(self.sock, req)

    def recv_reply(self, timeout: Optional[float] = None) -> ApiReply:
        self.sock.settimeout(timeout)
        try:
            return safetcp.recv_msg_sync(self.sock)
        finally:
            self.sock.settimeout(None)

    def close(self) -> None:
        self.sock.close()


class GenericEndpoint:
    """Manager-guided endpoint with redirect-aware server selection."""

    def __init__(self, manager_addr: Tuple[str, int],
                 server_id: Optional[int] = None):
        self.ctrl = ClientCtrlStub(manager_addr)
        self.id = self.ctrl.id
        self.prefer = server_id
        self.api: Optional[ClientApiStub] = None
        self.servers = {}
        self.current: Optional[int] = None

    def connect(self) -> None:
        info = self.ctrl.request(CtrlRequest("query_info"))
        if not info.servers:
            raise SummersetError("no servers joined yet")
        self.servers = info.servers
        target = self.prefer
        if target is None or target not in info.servers:
            target = (
                info.leader
                if info.leader is not None and info.leader in info.servers
                else sorted(info.servers)[0]
            )
        self._connect_to(target)

    def _connect_to(self, sid: int) -> None:
        if self.api is not None:
            self.api.close()
            self.api = None
        api_addr, _ = self.servers[sid]
        self.api = ClientApiStub(self.id, api_addr)
        self.current = sid

    def reconnect(self, sid: Optional[int] = None) -> None:
        if sid is not None and sid in self.servers:
            self._connect_to(sid)
        else:
            self.connect()

    def rotate(self, avoid: Optional[int] = None) -> None:
        """Fail over to a different server after a timeout.

        Parity: the reference tester leaves + reconnects around faults
        (tester.rs:429-433) and the endpoint re-queries the manager
        (endpoint.rs:17-54).  Prefers the manager's current leader unless
        that is the server being avoided (e.g. it just got paused and the
        manager has not seen the new leader yet), else round-robins to the
        next id so repeated timeouts walk the whole membership."""
        leader = None
        try:
            info = self.ctrl.request(CtrlRequest("query_info"), timeout=5)
            if info.servers:
                self.servers = info.servers
            leader = info.leader
        except Exception:
            pass
        if not self.servers:
            return
        if avoid is None:
            avoid = self.current
        cands = sorted(self.servers)
        order = []
        if leader is not None and leader in self.servers and leader != avoid:
            order.append(leader)
        start = cands.index(avoid) if avoid in cands else -1
        for off in range(1, len(cands) + 1):
            cand = cands[(start + off) % len(cands)]
            if cand != avoid and cand not in order:
                order.append(cand)
        if avoid in cands:
            order.append(avoid)  # last resort: everything else unreachable
        for cand in order:
            try:
                self._connect_to(cand)
                return
            except OSError:
                continue

    def send_req(self, req_id: int, cmd: Command) -> None:
        assert self.api is not None, "connect() first"
        self.api.send_req(ApiRequest("req", req_id=req_id, cmd=cmd))

    def send_conf(self, req_id: int, conf_delta: dict) -> None:
        """Issue a ConfChange (parity: ApiRequest::Conf,
        external.rs:106-121)."""
        assert self.api is not None, "connect() first"
        self.api.send_req(
            ApiRequest("conf", req_id=req_id, conf_delta=conf_delta)
        )

    def recv_reply(self, timeout: Optional[float] = None) -> ApiReply:
        assert self.api is not None
        return self.api.recv_reply(timeout=timeout)

    def leave(self, keep_ctrl: bool = False) -> None:
        if self.api is not None:
            try:
                self.api.send_req(ApiRequest("leave"))
                self.api.recv_reply(timeout=2)
            except Exception:
                pass
            self.api.close()
            self.api = None
        if not keep_ctrl:
            self.ctrl.close()
