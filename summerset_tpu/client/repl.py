"""ClientRepl / ClientMess: interactive CLI + one-shot perturbations.

Parity: reference ``summerset_client/src/clients/repl.rs`` (get/put/stop
prompt loop) and ``clients/mess.rs:16-45`` (one-shot pause/resume sets,
conf changes, a single write).
"""

from __future__ import annotations

import sys
from typing import List, Optional, Tuple

from ..host.messages import CtrlRequest
from .drivers import DriverClosedLoop
from .endpoint import GenericEndpoint


class ClientRepl:
    HELP = (
        "commands: get <key> | put <key> <value> | reconnect | help | exit"
    )

    def __init__(self, manager_addr: Tuple[str, int]):
        self.ep = GenericEndpoint(manager_addr)
        self.ep.connect()
        self.drv = DriverClosedLoop(self.ep)

    def run(self, stdin=None, stdout=None) -> None:
        stdin = stdin or sys.stdin
        stdout = stdout or sys.stdout
        print(self.HELP, file=stdout)
        for line in stdin:
            parts = line.split()
            if not parts:
                continue
            try:
                if parts[0] == "exit":
                    break
                elif parts[0] == "help":
                    print(self.HELP, file=stdout)
                elif parts[0] == "reconnect":
                    self.ep.reconnect()
                    print(f"connected to {self.ep.current}", file=stdout)
                elif parts[0] == "get":
                    rep = self.drv.get(parts[1])
                    val = rep.result.value if rep.result else None
                    print(f"{rep.kind}: {parts[1]} = {val}", file=stdout)
                elif parts[0] == "put":
                    rep = self.drv.put(parts[1], " ".join(parts[2:]))
                    print(f"{rep.kind}: {parts[1]} set", file=stdout)
                else:
                    print(self.HELP, file=stdout)
            except Exception as e:
                print(f"error: {e}", file=stdout)
        self.ep.leave()


class ClientMess:
    """One-shot cluster perturbation (parity: mess.rs:16-45)."""

    def __init__(self, manager_addr: Tuple[str, int]):
        self.manager_addr = manager_addr

    def run(
        self,
        pause: Optional[List[int]] = None,
        resume: Optional[List[int]] = None,
        write: Optional[Tuple[str, str]] = None,
        responders: Optional[List[int]] = None,
        leader: Optional[int] = None,
    ) -> None:
        ep = GenericEndpoint(self.manager_addr)
        if pause is not None:
            ep.ctrl.request(
                CtrlRequest("pause_servers", servers=pause or None)
            )
        if resume is not None:
            ep.ctrl.request(
                CtrlRequest("resume_servers", servers=resume or None)
            )
        if responders is not None or leader is not None:
            # responders-conf change through the data plane (mess.rs
            # conf perturbations -> ConfChange)
            ep.connect()
            delta = {}
            if responders is not None:
                delta["responders"] = responders
            if leader is not None:
                delta["leader"] = leader
            DriverClosedLoop(ep).conf_change(delta)
        if write is not None:
            if ep.api is None:
                ep.connect()
            DriverClosedLoop(ep).checked_put(write[0], write[1])
        ep.leave()
