"""ClientBench: throughput/latency benchmark mode.

Parity: reference ``summerset_client/src/clients/bench.rs`` — open-loop
driver with target frequency pacing (0 = unlimited), put ratio, value
sizes with "t1:v1/t2:v2" schedules, key count with preloading, normal /
uniform size distributions, optional YCSB-style trace replay, and
periodic interval stats lines ``tput ... lat p50/p99 ...`` parsed by the
orchestration scripts (bench.rs:28-130).
"""

from __future__ import annotations

import random
import string
import time
from typing import List, Optional, Tuple

from ..host.statemach import Command
from ..host.telemetry import Histogram
from ..utils.logging import pf_info, pf_logger
from .drivers import DriverClosedLoop, DriverOpenLoop
from .endpoint import GenericEndpoint

logger = pf_logger("bench")


def load_ycsb_trace(path: str) -> List[Tuple[str, str, Optional[str]]]:
    """Load a YCSB run log into a ClientBench trace.

    Parity: the reference bench replays YCSB trace files
    (``clients/bench.rs`` ycsb trace support; lines shaped
    ``READ usertable <key> ...`` / ``UPDATE usertable <key> [field=...]``
    / ``INSERT ...``).  SCANs replay as ordered range reads (the third
    tuple slot carries the YCSB scan count as a string); for plan-level
    replay with digest stamping use ``WorkloadPlan.from_trace``."""
    trace: List[Tuple[str, str, Optional[str]]] = []
    with open(path) as f:
        for line in f:
            toks = line.split()
            if len(toks) < 3:
                continue
            op = toks[0].upper()
            if op == "READ":
                trace.append(("get", toks[2], None))
            elif op == "SCAN":
                count = toks[3] if len(toks) > 3 and toks[3].isdigit() \
                    else "1"
                trace.append(("scan", toks[2], count))
            elif op in ("UPDATE", "INSERT"):
                val: Optional[str] = None
                if "[" in line:
                    val = line.split("[", 1)[1].rsplit("]", 1)[0].strip()
                trace.append(("put", toks[2], val))
    return trace


def parse_value_schedule(spec: str) -> List[Tuple[float, int]]:
    """"t1:v1/t2:v2" -> [(t_from, size)]; a bare "128" means a constant
    size from t=0 (bench.rs value-size schedule)."""
    out = []
    for seg in str(spec).split("/"):
        if ":" in seg:
            t, v = seg.split(":")
            out.append((float(t), int(v)))
        else:
            out.append((0.0, int(seg)))
    return sorted(out)


class ClientBench:
    def __init__(
        self,
        endpoint: GenericEndpoint,
        secs: float = 10.0,
        freq: float = 0.0,            # target reqs/sec; 0 = unlimited
        put_ratio: float = 0.5,
        value_size: str = "128",      # int or "t:v/t:v" schedule
        num_keys: int = 5,
        normal_stdev_ratio: float = 0.0,
        trace: Optional[List[Tuple[str, str, Optional[str]]]] = None,
        interval: float = 0.1,
        seed: int = 0,
        opgen=None,
    ):
        self.ep = endpoint
        self.secs = secs
        self.freq = freq
        self.put_ratio = put_ratio
        self.schedule = parse_value_schedule(value_size)
        self.num_keys = num_keys
        self.stdev = normal_stdev_ratio
        self.trace = trace
        self.interval = interval
        self.rng = random.Random(seed)
        # workload plane (host/workload.WorkloadPlan.opstream): when an
        # op stream is given it owns kinds/keys/value sizes and the
        # uniform knobs above are ignored — uniform stays the default so
        # committed TPUTLAT/HOSTBENCH trajectories remain comparable
        self.opgen = opgen
        self.keys = (
            list(opgen.keys) if opgen is not None
            else [f"k{i}" for i in range(num_keys)]
        )

    def _value(self, now: float) -> str:
        size = self.schedule[0][1]
        for t, v in self.schedule:
            if now >= t:
                size = v
        if self.stdev > 0:
            size = max(1, int(self.rng.gauss(size, size * self.stdev)))
        return "".join(
            self.rng.choices(string.ascii_lowercase, k=size)
        )

    def _sized_value(self, size: int) -> str:
        return "".join(
            self.rng.choices(string.ascii_lowercase, k=max(1, size))
        )

    def _next_cmd(self, now: float, i: int) -> Command:
        if self.opgen is not None:
            kind, key, size = self.opgen.next()
            if kind == "put":
                return Command("put", key, self._sized_value(size))
            if kind == "scan":
                # ordered range read: start at the picked key, length
                # capped by the plan's scan_max (YCSB-E start+count)
                return Command("scan", key, limit=max(1, int(size)))
            return Command("get", key)
        if self.trace:
            op, key, val = self.trace[i % len(self.trace)]
            if op == "put":
                return Command("put", key, val or self._value(now))
            if op == "scan":
                return Command("scan", key,
                               limit=max(1, int(val or 1)))
            return Command("get", key)
        key = self.rng.choice(self.keys)
        if self.rng.random() < self.put_ratio:
            return Command("put", key, self._value(now))
        return Command("get", key)

    def run(self) -> dict:
        # preload every key once (bench.rs preloading) with the
        # closed-loop driver: it follows redirects/timeouts, where an
        # open-loop pipeline would strand its inflight window on the
        # first redirect reconnect
        pre = DriverClosedLoop(self.ep)
        for k in self.keys:
            pre.checked_put(k, self._value(0.0))
        drv = DriverOpenLoop(self.ep)

        t_start = time.monotonic()
        issued = acked = 0
        lats: List[float] = []
        # interval stats ride a cumulative exponential histogram and
        # its since() window view (host/telemetry.py) instead of a
        # per-interval sample list: a long soak's interval lines cost
        # O(1) memory, while the exact end-of-run summary still sorts
        # the full `lats` list
        lat_hist = Histogram()
        int_prev = lat_hist.copy()
        int_acked = 0
        t_int = t_start
        pace = 1.0 / self.freq if self.freq > 0 else 0.0
        t_next = t_start
        while True:
            now = time.monotonic()
            if now - t_start >= self.secs:
                break
            if pace == 0.0 or now >= t_next:
                drv.issue(self._next_cmd(now - t_start, issued))
                issued += 1
                t_next += pace
            budget = max(0.0, min(
                (t_next - now) if pace else 0.001, 0.01
            ))
            rep = drv.wait_reply(timeout=budget or 0.001)
            if rep is not None and rep.kind == "success":
                acked += 1
                int_acked += 1
                lats.append(rep.latency)
                lat_hist.observe(int(rep.latency * 1e6))
            if now - t_int >= self.interval:
                dt = now - t_int
                tput = int_acked / dt
                win = lat_hist.since(int_prev)
                p50 = win.quantile(0.50) / 1e6
                p99 = win.quantile(0.99) / 1e6
                pf_info(
                    logger,
                    f"tput {tput:10.2f} reqs/s  "
                    f"lat p50 {p50 * 1e3:7.3f} p99 {p99 * 1e3:7.3f} ms",
                )
                t_int = now
                int_acked = 0
                int_prev = lat_hist.copy()

        # drain stragglers briefly
        t_end = time.monotonic() + 1.0
        while drv.inflight and time.monotonic() < t_end:
            rep = drv.wait_reply(timeout=0.1)
            if rep is not None and rep.kind == "success":
                acked += 1
                lats.append(rep.latency)
        dt = time.monotonic() - t_start
        p50, p99 = _pctiles(lats)
        summary = {
            "issued": issued,
            "acked": acked,
            "tput": acked / dt,
            "lat_p50_ms": p50 * 1e3,
            "lat_p99_ms": p99 * 1e3,
        }
        pf_info(
            logger,
            f"total tput {summary['tput']:.2f} reqs/s  "
            f"p50 {summary['lat_p50_ms']:.3f} p99 "
            f"{summary['lat_p99_ms']:.3f} ms",
        )
        return summary


def _pctiles(lats: List[float]) -> Tuple[float, float]:
    if not lats:
        return 0.0, 0.0
    s = sorted(lats)
    return s[len(s) // 2], s[min(len(s) - 1, int(len(s) * 0.99))]
