"""Closed-loop and open-loop request drivers.

Parity: reference ``summerset_client/src/drivers/`` —
``DriverClosedLoop`` issues one outstanding request with a timeout timer
(closed_loop.rs; ``DriverReply::{Success{latency}, Redirect, Timeout}``,
drivers/mod.rs:12-40); ``DriverOpenLoop`` pipelines issues and acks
(open_loop.rs) with would-block-style retry awareness.

``DriverOpenLoopPaced`` is the workload plane's driver
(``host/workload.WorkloadPlan``): a shed-aware pipelined driver for
open-loop arrival schedules — arrivals keep coming at the offered rate
regardless of replies, and an ``ApiReply(kind="shed")`` negative ack
gates issuing until the server's retry-after hint (with seeded jitter)
has elapsed, so backed-off clients neither hot-retry into a full queue
nor synchronize into a thundering herd when it drains.
"""

from __future__ import annotations

import dataclasses
import random
import socket
import time
from typing import Dict, List, Optional, Tuple

from ..host.statemach import Command, CommandResult
from .endpoint import GenericEndpoint


class Backoff:
    """Jittered exponential backoff for retry loops.

    The old fixed ``sleep(0.1)`` hot-retry turned every fault window into
    a synchronized thundering herd against whichever server the clients
    rotated to — under nemesis schedules the herd itself delayed
    recovery.  Full jitter (AWS-style: sleep uniform in (0, cur]) breaks
    the synchronization; the seed keeps a client's delay *sequence*
    reproducible run to run."""

    def __init__(self, base: float = 0.05, cap: float = 1.0,
                 seed: int = 0):
        self.base = base
        self.cap = cap
        self._cur = base
        self._rng = random.Random(seed)

    def reset(self) -> None:
        self._cur = self.base

    def sleep(self) -> float:
        d = self._rng.uniform(0.0, self._cur)
        self._cur = min(self._cur * 2.0, self.cap)
        time.sleep(d)
        return d

    def sleep_hint(self, hint_s: float) -> float:
        """Honor a server retry-after hint with jitter (uniform in
        [0.5, 1.5] x hint, capped): the hint centers the backoff on the
        server's own drain estimate, the jitter de-synchronizes the
        herd of shed clients that all received the same hint."""
        d = min(max(hint_s, 0.001) * self._rng.uniform(0.5, 1.5),
                self.cap)
        time.sleep(d)
        return d


@dataclasses.dataclass
class DriverReply:
    # success | redirect | timeout | failure (server refused) |
    # shed (ingress backpressure: definitely NOT executed; honor
    # retry_after before retrying — the server is healthy, rotating
    # away from it would just overload the next one) |
    # disconnect (connection dead — callers must reconnect/rotate, a
    # retry in place can never succeed)
    kind: str
    latency: float = 0.0          # seconds (success)
    result: Optional[CommandResult] = None
    redirect: Optional[int] = None
    local: bool = False           # served as a leased local read
    retry_after: float = 0.0      # seconds (shed backoff hint)


class DriverClosedLoop:
    def __init__(self, endpoint: GenericEndpoint, timeout: float = 5.0):
        self.ep = endpoint
        self.timeout = timeout
        self.next_req = 0
        self.backoff = Backoff(seed=endpoint.id)

    def _issue(self, cmd: Command) -> DriverReply:
        rid = self.next_req
        self.next_req += 1
        t0 = time.monotonic()
        try:
            self.ep.send_req(rid, cmd)
        except Exception:
            return DriverReply("disconnect")
        deadline = t0 + self.timeout
        while True:
            budget = deadline - time.monotonic()
            if budget <= 0:
                return DriverReply("timeout")
            try:
                rep = self.ep.recv_reply(timeout=budget)
            except socket.timeout:
                # the budget expired on a healthy connection WITH ZERO
                # frame bytes consumed (safetcp raises SummersetError for
                # a mid-frame timeout, taken by the branch below): the
                # stream is still frame-aligned, so this is the TIMEOUT
                # kind and a retry in place is safe
                return DriverReply("timeout")
            except Exception:
                # includes a timeout that fired mid-frame: the api stub's
                # stream is no longer frame-aligned and a retry in place
                # would unpickle garbage — callers must reconnect/rotate
                return DriverReply("disconnect")
            if rep.req_id != rid:
                continue  # stale reply from a previous timeout
            if rep.kind == "redirect":
                # the reconnect is bounded by THIS request's remaining
                # budget: a black-holed hinted server must not stretch
                # the call past self.timeout (the connect used to ride a
                # fixed 15s socket timeout, overshooting the deadline)
                self.ep.follow_redirect(rep.redirect, deadline=deadline)
                return DriverReply("redirect", redirect=rep.redirect)
            if rep.kind == "shed":
                # ingress backpressure: the request never entered the
                # queue (guaranteed not executed); the caller should
                # back off by the hint, not rotate — the server is
                # healthy, just full
                return DriverReply(
                    "shed",
                    retry_after=max(rep.retry_after_ms, 1) / 1e3,
                )
            if rep.kind in ("reply", "conf") and rep.success:
                return DriverReply(
                    "success",
                    latency=time.monotonic() - t0,
                    result=rep.result,
                    local=rep.local,
                )
            return DriverReply("failure")

    def get(self, key: str) -> DriverReply:
        return self._issue(Command("get", key))

    def put(self, key: str, value: str) -> DriverReply:
        return self._issue(Command("put", key, value))

    def scan(self, start: str, end: Optional[str] = None,
             limit: int = 0) -> DriverReply:
        """Ordered range read over ``[start, end)``: the reply's
        ``result.items`` is the sorted (key, value) cut."""
        return self._issue(Command("scan", start, end=end,
                                   limit=int(limit)))

    def conf_change(self, conf_delta: dict, retries: int = 20
                    ) -> DriverReply:
        """Drive a ConfChange to completion through redirects/timeouts
        (parity: the reference mess/tester clients' conf flow,
        clients/mess.rs:16-45)."""
        for _ in range(retries):
            rid = self.next_req
            self.next_req += 1
            t0 = time.monotonic()
            try:
                self.ep.send_conf(rid, conf_delta)
            except Exception:
                self._failover(DriverReply("disconnect"))
                self.backoff.sleep()
                continue
            deadline = t0 + max(self.timeout, 15.0)  # conf rides the log
            rep = None
            while True:
                budget = deadline - time.monotonic()
                if budget <= 0:
                    rep = DriverReply("timeout")
                    break
                try:
                    raw = self.ep.recv_reply(timeout=budget)
                except socket.timeout:
                    rep = DriverReply("timeout")
                    break
                except Exception:
                    rep = DriverReply("disconnect")
                    break
                if raw.req_id != rid:
                    continue
                if raw.kind == "redirect":
                    self.ep.follow_redirect(
                        raw.redirect, deadline=deadline
                    )
                    rep = DriverReply("redirect", redirect=raw.redirect)
                    break
                rep = (
                    DriverReply("success",
                                latency=time.monotonic() - t0)
                    if raw.success else DriverReply("failure")
                )
                break
            if rep.kind == "success":
                self.backoff.reset()
                return rep
            self._failover(rep)
            self.backoff.sleep()
        raise AssertionError("conf_change failed after retries")

    def _failover(self, rep: DriverReply) -> None:
        """Stop retrying against a dead/paused server: a timeout or a
        connection failure rotates the endpoint to a different server
        (parity: tester.rs:429-433 leave+reconnect around faults; the
        redirect case already reconnected inside ``_issue``).  The walk
        is bounded by one request budget so a stack of black-holed
        candidates cannot stall the caller's retry loop."""
        if rep.kind in ("timeout", "failure", "disconnect"):
            try:
                self.ep.rotate(
                    deadline=time.monotonic() + self.timeout
                )
            except Exception:
                pass

    def _retry_pause(self, rep: DriverReply) -> None:
        """Between-retry wait: sheds honor the server's retry-after
        hint (jittered; no rotation — the server is healthy, just
        full), everything else takes the exponential backoff after the
        usual failover rotation."""
        if rep.kind == "shed":
            self.backoff.sleep_hint(rep.retry_after)
        else:
            self._failover(rep)
            self.backoff.sleep()

    def checked_put(self, key: str, value: str, retries: int = 20):
        """Retry through redirects/timeouts until acked (tester helper,
        parity: tester.rs checked_put).  Retries back off with jitter
        (see Backoff) instead of hot-spinning on a faulted cluster."""
        for _ in range(retries):
            rep = self.put(key, value)
            if rep.kind == "success":
                self.backoff.reset()
                return rep
            self._retry_pause(rep)
        raise AssertionError(f"checked_put({key}) failed after retries")

    def checked_get(self, key: str, expect: Optional[str],
                    retries: int = 20):
        for _ in range(retries):
            rep = self.get(key)
            if rep.kind == "success":
                got = rep.result.value if rep.result else None
                assert got == expect, f"get({key}) = {got} != {expect}"
                self.backoff.reset()
                return rep
            self._retry_pause(rep)
        raise AssertionError(f"checked_get({key}) failed after retries")


class DriverOpenLoop:
    """Pipelined issue/ack driver (parity: open_loop.rs)."""

    def __init__(self, endpoint: GenericEndpoint, timeout: float = 5.0):
        self.ep = endpoint
        self.timeout = timeout
        self.next_req = 0
        self.inflight: Dict[int, float] = {}

    def issue(self, cmd: Command) -> int:
        rid = self.next_req
        self.next_req += 1
        self.ep.send_req(rid, cmd)
        self.inflight[rid] = time.monotonic()
        return rid

    def wait_reply(self, timeout: Optional[float] = None
                   ) -> Optional[DriverReply]:
        try:
            rep = self.ep.recv_reply(
                timeout=self.timeout if timeout is None else timeout
            )
        except Exception:
            return None
        t0 = self.inflight.pop(rep.req_id, None)
        if rep.kind == "redirect":
            self.ep.reconnect(rep.redirect)
            return DriverReply("redirect", redirect=rep.redirect)
        if rep.kind == "shed":
            # negative ack (never executed) — a bench counting this as
            # success would fold refused ops into the very overload
            # curves the workload classes exist to measure
            return DriverReply(
                "shed", retry_after=max(rep.retry_after_ms, 1) / 1e3,
            )
        if rep.kind not in ("reply", "conf") or not rep.success:
            return DriverReply("failure")
        return DriverReply(
            "success",
            latency=(time.monotonic() - t0) if t0 else 0.0,
            result=rep.result,
        )


class DriverOpenLoopPaced:
    """Shed-aware pipelined driver for open-loop workload schedules
    (``host/workload.WorkloadPlan``): the caller paces arrivals (the
    plan's phase table x the wall clock is the runner's business), this
    driver owns the inflight window, reply matching, shed gating, and
    per-op deadlines.

    Recording semantics for the workload soak's ``utils/linearize``
    histories (returned per reply so the caller can record):

    - ``success``  — acked; record with [t_inv, t_resp];
    - ``shed``     — negatively acked (guaranteed never proposed);
      record as a shed op (the checker EXCLUDES it — a get observing
      its value is then a linearizability violation) and gate issuing
      until the jittered retry-after elapses;
    - ``redirect`` — refused without proposing; not recorded (the
      driver reconnects toward the hint);
    - expiry (``expired()``) — no reply within ``timeout``: a put may
      or may not have executed, record UNACKED.
    """

    def __init__(self, endpoint: GenericEndpoint, timeout: float = 5.0,
                 seed: int = 0, max_inflight: int = 128):
        self.ep = endpoint
        self.timeout = timeout
        self.next_req = 0
        # rid -> {"kind", "key", "value", "t0", "deadline"}
        self.inflight: Dict[int, dict] = {}
        # bounded window (YCSB-style): past it, arrivals are dropped
        # client-side and counted — an unbounded window under overload
        # would just move the unbounded queue into the client
        self.max_inflight = max(1, int(max_inflight))
        self._rng = random.Random(seed * 65537 + 3)
        self.hold_until = 0.0  # shed gate (monotonic seconds)
        self.counts = {
            "issued": 0, "acked": 0, "shed": 0, "expired": 0,
            "redirect": 0, "failure": 0, "held": 0, "window": 0,
        }

    def gated(self, now: float) -> bool:
        """Is issuing currently suppressed by a shed retry-after
        hint?  (Open-loop arrivals landing inside the gate are counted
        ``held`` by the caller and dropped — the client-side half of
        graceful degradation.)"""
        return now < self.hold_until

    def issue(self, kind: str, key: str,
              value: Optional[str] = None,
              end: Optional[str] = None) -> Optional[int]:
        """Send one op; returns its rid, or None when the connection
        died at send (the op never left — nothing to record; the driver
        rotates so the next arrival has a live socket)."""
        if len(self.inflight) >= self.max_inflight:
            self.counts["window"] += 1
            return None
        rid = self.next_req
        self.next_req += 1
        if kind == "put":
            cmd = Command("put", key, value)
        elif kind == "scan":
            # open-loop scans carry the length in ``value`` (workload
            # OpStream emits ("scan", start_key, length)): a limit cap
            # with an optional end bound — the YCSB-E shape.  Recorder
            # callers pass the plan keyspace's upper bound as ``end`` so
            # the observed cut never strays into harness keys whose
            # writes the checked history does not carry
            cmd = Command("scan", key, end=end,
                          limit=max(1, int(value or 1)))
        else:
            cmd = Command("get", key)
        try:
            self.ep.send_req(rid, cmd)
        except Exception:
            self._reconnect()
            return None
        now = time.monotonic()
        self.inflight[rid] = {
            "kind": kind, "key": key, "value": value,
            "limit": cmd.limit, "end": cmd.end, "t0": now,
            "deadline": now + self.timeout,
        }
        self.counts["issued"] += 1
        return rid

    def _reconnect(self) -> None:
        try:
            self.ep.rotate(deadline=time.monotonic() + 1.0)
        except Exception:
            pass

    def poll(self, budget: float) -> List[Tuple[dict, DriverReply]]:
        """Drain replies for up to ``budget`` seconds; returns
        ``[(inflight-info, DriverReply)]`` for every matched reply."""
        out: List[Tuple[dict, DriverReply]] = []
        end = time.monotonic() + max(budget, 0.0)
        while True:
            rem = end - time.monotonic()
            if rem <= 0:
                break
            try:
                rep = self.ep.recv_reply(timeout=max(rem, 0.001))
            except socket.timeout:
                break
            except Exception:
                # dead/mid-frame connection: inflight ops will expire
                # as unacked; reconnect for the next arrivals
                self._reconnect()
                break
            info = self.inflight.pop(rep.req_id, None)
            if info is None:
                continue  # stale reply from before a reconnect
            now = time.monotonic()
            if rep.kind == "shed":
                hint = max(rep.retry_after_ms, 1) / 1e3
                self.hold_until = max(
                    self.hold_until,
                    now + hint * self._rng.uniform(0.5, 1.5),
                )
                self.counts["shed"] += 1
                out.append((info, DriverReply(
                    "shed", retry_after=hint,
                )))
            elif rep.kind == "redirect":
                self.counts["redirect"] += 1
                self.ep.follow_redirect(rep.redirect, deadline=now + 1.0)
                out.append((info, DriverReply(
                    "redirect", redirect=rep.redirect,
                )))
            elif rep.kind in ("reply", "conf") and rep.success:
                self.counts["acked"] += 1
                out.append((info, DriverReply(
                    "success", latency=now - info["t0"],
                    result=rep.result, local=rep.local,
                )))
            else:
                self.counts["failure"] += 1
                out.append((info, DriverReply("failure")))
            if not self.inflight:
                break
        return out

    def expired(self) -> List[dict]:
        """Pop and return every inflight op past its deadline (puts
        among them must be recorded UNACKED — they may have executed)."""
        now = time.monotonic()
        out = []
        for rid, info in list(self.inflight.items()):
            if now > info["deadline"]:
                out.append(self.inflight.pop(rid))
        self.counts["expired"] += len(out)
        return out
