"""Closed-loop and open-loop request drivers.

Parity: reference ``summerset_client/src/drivers/`` —
``DriverClosedLoop`` issues one outstanding request with a timeout timer
(closed_loop.rs; ``DriverReply::{Success{latency}, Redirect, Timeout}``,
drivers/mod.rs:12-40); ``DriverOpenLoop`` pipelines issues and acks
(open_loop.rs) with would-block-style retry awareness.
"""

from __future__ import annotations

import dataclasses
import random
import socket
import time
from typing import Dict, Optional

from ..host.statemach import Command, CommandResult
from .endpoint import GenericEndpoint


class Backoff:
    """Jittered exponential backoff for retry loops.

    The old fixed ``sleep(0.1)`` hot-retry turned every fault window into
    a synchronized thundering herd against whichever server the clients
    rotated to — under nemesis schedules the herd itself delayed
    recovery.  Full jitter (AWS-style: sleep uniform in (0, cur]) breaks
    the synchronization; the seed keeps a client's delay *sequence*
    reproducible run to run."""

    def __init__(self, base: float = 0.05, cap: float = 1.0,
                 seed: int = 0):
        self.base = base
        self.cap = cap
        self._cur = base
        self._rng = random.Random(seed)

    def reset(self) -> None:
        self._cur = self.base

    def sleep(self) -> float:
        d = self._rng.uniform(0.0, self._cur)
        self._cur = min(self._cur * 2.0, self.cap)
        time.sleep(d)
        return d


@dataclasses.dataclass
class DriverReply:
    # success | redirect | timeout | failure (server refused) |
    # disconnect (connection dead — callers must reconnect/rotate, a
    # retry in place can never succeed)
    kind: str
    latency: float = 0.0          # seconds (success)
    result: Optional[CommandResult] = None
    redirect: Optional[int] = None
    local: bool = False           # served as a leased local read


class DriverClosedLoop:
    def __init__(self, endpoint: GenericEndpoint, timeout: float = 5.0):
        self.ep = endpoint
        self.timeout = timeout
        self.next_req = 0
        self.backoff = Backoff(seed=endpoint.id)

    def _issue(self, cmd: Command) -> DriverReply:
        rid = self.next_req
        self.next_req += 1
        t0 = time.monotonic()
        try:
            self.ep.send_req(rid, cmd)
        except Exception:
            return DriverReply("disconnect")
        deadline = t0 + self.timeout
        while True:
            budget = deadline - time.monotonic()
            if budget <= 0:
                return DriverReply("timeout")
            try:
                rep = self.ep.recv_reply(timeout=budget)
            except socket.timeout:
                # the budget expired on a healthy connection WITH ZERO
                # frame bytes consumed (safetcp raises SummersetError for
                # a mid-frame timeout, taken by the branch below): the
                # stream is still frame-aligned, so this is the TIMEOUT
                # kind and a retry in place is safe
                return DriverReply("timeout")
            except Exception:
                # includes a timeout that fired mid-frame: the api stub's
                # stream is no longer frame-aligned and a retry in place
                # would unpickle garbage — callers must reconnect/rotate
                return DriverReply("disconnect")
            if rep.req_id != rid:
                continue  # stale reply from a previous timeout
            if rep.kind == "redirect":
                hint = rep.redirect
                self.ep.note_leader(hint)
                # the reconnect is bounded by THIS request's remaining
                # budget: a black-holed hinted server must not stretch
                # the call past self.timeout (the connect used to ride a
                # fixed 15s socket timeout, overshooting the deadline)
                budget = deadline - time.monotonic()
                try:
                    if budget <= 0:
                        pass  # out of budget: the caller's retry rotates
                    elif (
                        hint is not None and hint >= 0
                        and hint != self.ep.current
                    ):
                        self.ep.reconnect(hint, timeout=budget)
                    else:
                        # no hint, or the server pointed at itself
                        # (leadership unsettled): walk the membership
                        self.ep.rotate(deadline=deadline)
                except Exception:
                    pass  # hinted server down: the next retry rotates
                return DriverReply("redirect", redirect=rep.redirect)
            if rep.kind in ("reply", "conf") and rep.success:
                return DriverReply(
                    "success",
                    latency=time.monotonic() - t0,
                    result=rep.result,
                    local=rep.local,
                )
            return DriverReply("failure")

    def get(self, key: str) -> DriverReply:
        return self._issue(Command("get", key))

    def put(self, key: str, value: str) -> DriverReply:
        return self._issue(Command("put", key, value))

    def conf_change(self, conf_delta: dict, retries: int = 20
                    ) -> DriverReply:
        """Drive a ConfChange to completion through redirects/timeouts
        (parity: the reference mess/tester clients' conf flow,
        clients/mess.rs:16-45)."""
        for _ in range(retries):
            rid = self.next_req
            self.next_req += 1
            t0 = time.monotonic()
            try:
                self.ep.send_conf(rid, conf_delta)
            except Exception:
                self._failover(DriverReply("disconnect"))
                self.backoff.sleep()
                continue
            deadline = t0 + max(self.timeout, 15.0)  # conf rides the log
            rep = None
            while True:
                budget = deadline - time.monotonic()
                if budget <= 0:
                    rep = DriverReply("timeout")
                    break
                try:
                    raw = self.ep.recv_reply(timeout=budget)
                except socket.timeout:
                    rep = DriverReply("timeout")
                    break
                except Exception:
                    rep = DriverReply("disconnect")
                    break
                if raw.req_id != rid:
                    continue
                if raw.kind == "redirect":
                    hint = raw.redirect
                    self.ep.note_leader(hint)
                    budget = deadline - time.monotonic()
                    try:
                        if budget <= 0:
                            pass
                        elif (
                            hint is not None and hint >= 0
                            and hint != self.ep.current
                        ):
                            self.ep.reconnect(hint, timeout=budget)
                        else:
                            self.ep.rotate(deadline=deadline)
                    except Exception:
                        pass
                    rep = DriverReply("redirect", redirect=hint)
                    break
                rep = (
                    DriverReply("success",
                                latency=time.monotonic() - t0)
                    if raw.success else DriverReply("failure")
                )
                break
            if rep.kind == "success":
                self.backoff.reset()
                return rep
            self._failover(rep)
            self.backoff.sleep()
        raise AssertionError("conf_change failed after retries")

    def _failover(self, rep: DriverReply) -> None:
        """Stop retrying against a dead/paused server: a timeout or a
        connection failure rotates the endpoint to a different server
        (parity: tester.rs:429-433 leave+reconnect around faults; the
        redirect case already reconnected inside ``_issue``).  The walk
        is bounded by one request budget so a stack of black-holed
        candidates cannot stall the caller's retry loop."""
        if rep.kind in ("timeout", "failure", "disconnect"):
            try:
                self.ep.rotate(
                    deadline=time.monotonic() + self.timeout
                )
            except Exception:
                pass

    def checked_put(self, key: str, value: str, retries: int = 20):
        """Retry through redirects/timeouts until acked (tester helper,
        parity: tester.rs checked_put).  Retries back off with jitter
        (see Backoff) instead of hot-spinning on a faulted cluster."""
        for _ in range(retries):
            rep = self.put(key, value)
            if rep.kind == "success":
                self.backoff.reset()
                return rep
            self._failover(rep)
            self.backoff.sleep()
        raise AssertionError(f"checked_put({key}) failed after retries")

    def checked_get(self, key: str, expect: Optional[str],
                    retries: int = 20):
        for _ in range(retries):
            rep = self.get(key)
            if rep.kind == "success":
                got = rep.result.value if rep.result else None
                assert got == expect, f"get({key}) = {got} != {expect}"
                self.backoff.reset()
                return rep
            self._failover(rep)
            self.backoff.sleep()
        raise AssertionError(f"checked_get({key}) failed after retries")


class DriverOpenLoop:
    """Pipelined issue/ack driver (parity: open_loop.rs)."""

    def __init__(self, endpoint: GenericEndpoint, timeout: float = 5.0):
        self.ep = endpoint
        self.timeout = timeout
        self.next_req = 0
        self.inflight: Dict[int, float] = {}

    def issue(self, cmd: Command) -> int:
        rid = self.next_req
        self.next_req += 1
        self.ep.send_req(rid, cmd)
        self.inflight[rid] = time.monotonic()
        return rid

    def wait_reply(self, timeout: Optional[float] = None
                   ) -> Optional[DriverReply]:
        try:
            rep = self.ep.recv_reply(
                timeout=self.timeout if timeout is None else timeout
            )
        except Exception:
            return None
        t0 = self.inflight.pop(rep.req_id, None)
        if rep.kind == "redirect":
            self.ep.reconnect(rep.redirect)
            return DriverReply("redirect", redirect=rep.redirect)
        return DriverReply(
            "success",
            latency=(time.monotonic() - t0) if t0 else 0.0,
            result=rep.result,
        )
