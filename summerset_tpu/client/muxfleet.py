"""MuxClientFleet: a selector-multiplexed closed-loop client fleet.

Ten thousand concurrent closed-loop clients cannot be ten thousand
threads (stack memory + scheduler churn alone sink the box long before
the serving path is the bottleneck).  This fleet multiplexes N virtual
clients over a handful of worker threads, each owning one
``selectors.DefaultSelector`` (epoll on Linux) and a slice of the
clients — the same event-loop shape the serving side's asyncio servants
already use, so client count stops being a thread count.

Each virtual client is a tiny nonblocking state machine speaking the
standard safetcp frame format (8-byte BE length + body, where the body
is the compact wirecodec form for hot ``ApiRequest``/``ApiReply``
kinds and pickle otherwise — replies dispatch per frame on the body's
tag byte, so the fleet follows whatever the serving tier emits):

    connect -> send id frame -> { send one op, await its reply } loop

Closed-loop semantics match ``DriverClosedLoop``: one outstanding op per
client; ``shed`` replies honor the server's retry-after hint with
jitter (the client parks, costing no socket traffic); ``redirect``
rotates to the next address; a reply timeout reconnects (round-robin)
and the op is NOT retried — like the threaded drivers, an unanswered op
is simply lost to the bench counters.

Client identities are minted from ``id_base`` upward (default well above
the manager-assigned cid space) — the api plane only uses the id as a
routing key, so a bench fleet does not need ten thousand manager ctrl
round-trips to exist.  ``setrlimit(RLIMIT_NOFILE)`` is raised on a
best-effort basis to fit the fleet's sockets.

Used by ``scripts/host_bench.py`` (the ``--clients 10000`` serving
bench, run in subprocess fleet workers so the serving process's GIL
never pays for client-side pickling).
"""

from __future__ import annotations

import random
import selectors
import socket
import string
import struct
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..host.messages import ApiReply, ApiRequest
from ..host.statemach import Command
from ..utils import safetcp, wirecodec

_LEN = struct.Struct(">Q")

#: default base for fleet-minted client ids: far above manager cids
#: (1000+) and the learner-id offset band (~500k)
FLEET_ID_BASE = 10_000_000


def raise_nofile(want: int) -> int:
    """Best-effort RLIMIT_NOFILE raise; returns the (possibly
    unchanged) soft limit so callers can scale down loudly instead of
    dying on EMFILE mid-connect."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        if soft < want:
            new_soft = min(max(want, soft), hard)
            resource.setrlimit(resource.RLIMIT_NOFILE, (new_soft, hard))
            soft = new_soft
        return soft
    except Exception:
        return 1 << 20  # unknown platform: assume plenty


def _frame(obj: Any, codec: bool = False) -> bytes:
    # the one codec-or-pickle framing decision lives in safetcp
    return safetcp.encode_frame(obj, codec=codec)


class _VClient:
    """One virtual closed-loop client (owned by exactly one worker)."""

    __slots__ = (
        "idx", "cid", "sock", "out", "buf", "state", "rid", "t_sent",
        "deadline", "addr_i", "rng", "stream", "park_until", "lats",
        "issued", "acked", "shed", "timeouts", "reconnects", "preload",
    )

    def __init__(self, idx: int, cid: int, seed: int, stream=None):
        self.idx = idx
        self.cid = cid
        self.sock: Optional[socket.socket] = None
        self.out = b""
        self.buf = bytearray()
        self.state = "idle"   # idle|connecting|serving|parked
        self.rid = 0
        self.t_sent = 0.0
        self.deadline = 0.0
        self.addr_i = idx     # round-robin start spread over targets
        self.rng = random.Random(seed * 65537 + idx)
        self.stream = stream  # optional WorkloadPlan OpStream
        self.park_until = 0.0
        self.lats: List[float] = []
        self.issued = 0
        self.acked = 0
        self.shed = 0
        self.timeouts = 0
        self.reconnects = 0
        self.preload = True   # first op: put own key (known-key GETs)


class MuxWorker:
    """One selector loop over a slice of the fleet."""

    def __init__(
        self,
        addrs: Sequence[Tuple[str, int]],
        clients: List[_VClient],
        secs: float,
        *,
        put_ratio: float = 0.5,
        value_size: int = 64,
        num_keys: int = 64,
        op_timeout: float = 5.0,
        connect_timeout: float = 10.0,
        think: float = 0.0,
        codec: Optional[bool] = None,
    ):
        # wire codec for outgoing hot requests (None = process default)
        self.codec = (
            wirecodec.default_on() if codec is None else bool(codec)
        )
        self.addrs = [tuple(a) for a in addrs]
        self.clients = clients
        self.secs = float(secs)
        self.put_ratio = float(put_ratio)
        self.value_size = int(value_size)
        self.num_keys = int(num_keys)
        self.op_timeout = float(op_timeout)
        self.connect_timeout = float(connect_timeout)
        # per-client think time between an ack and the next op
        # (jittered ±50%): real closed-loop fleets are not hot loops —
        # 10k concurrent clients at think=30 offer ~330 ops/s total,
        # which is how a connection-scaling bench keeps the offered
        # rate a controlled variable instead of "whatever saturates"
        self.think = max(0.0, float(think))
        self.sel = selectors.DefaultSelector()
        self.connected_peak = 0
        # conservative simultaneity floor: the MIN of established
        # connections across all post-ramp sweeps.  Per-worker minima
        # sum to a valid lower bound of total simultaneous concurrency
        # at EVERY instant of the measured window (each worker's live
        # count never dipped below its min), which per-worker PEAKS
        # taken at different instants do not give
        self.connected_min: Optional[int] = None

    # ------------------------------------------------------- op stream
    def _next_cmd(self, c: _VClient) -> Command:
        if c.preload:
            c.preload = False
            return Command(
                "put", f"mk{c.idx % self.num_keys}",
                "".join(c.rng.choices(string.ascii_lowercase,
                                      k=self.value_size)),
            )
        if c.stream is not None:
            kind, key, size = c.stream.next()
            if kind == "put":
                return Command("put", key, "".join(
                    c.rng.choices(string.ascii_lowercase, k=max(1, size))
                ))
            if kind == "scan":
                # ordered range read starting at the picked key; the
                # stream's size slot carries the YCSB-E scan length
                return Command("scan", key, limit=max(1, int(size)))
            return Command("get", key)
        key = f"mk{c.rng.randrange(self.num_keys)}"
        if c.rng.random() < self.put_ratio:
            return Command("put", key, "".join(
                c.rng.choices(string.ascii_lowercase, k=self.value_size)
            ))
        return Command("get", key)

    # ------------------------------------------------------- plumbing
    def _close(self, c: _VClient) -> None:
        if c.sock is not None:
            try:
                self.sel.unregister(c.sock)
            except (KeyError, ValueError):
                pass
            try:
                c.sock.close()
            except OSError:
                pass
        c.sock = None
        c.out = b""
        c.buf.clear()
        c.state = "idle"

    def _connect(self, c: _VClient, now: float) -> None:
        self._close(c)
        addr = self.addrs[c.addr_i % len(self.addrs)]
        c.addr_i += 1
        s = socket.socket()
        s.setblocking(False)
        try:
            s.connect(addr)
        except BlockingIOError:
            pass
        except OSError:
            s.close()
            c.park_until = now + 0.2
            c.state = "parked"
            return
        c.sock = s
        c.state = "connecting"
        c.deadline = now + self.connect_timeout
        # id frame + first op queued now; flushed as the socket opens
        c.out = _frame(c.cid)
        self.sel.register(s, selectors.EVENT_WRITE, c)

    def _issue(self, c: _VClient, now: float) -> None:
        cmd = self._next_cmd(c)
        c.rid += 1
        c.out += _frame(
            ApiRequest("req", req_id=c.rid, cmd=cmd), codec=self.codec
        )
        c.issued += 1
        c.t_sent = now
        c.deadline = now + self.op_timeout
        self._want_write(c)

    def _want_write(self, c: _VClient) -> None:
        if c.sock is None:
            return
        ev = selectors.EVENT_READ | (
            selectors.EVENT_WRITE if c.out else 0
        )
        try:
            self.sel.modify(c.sock, ev, c)
        except (KeyError, ValueError):
            pass

    # ----------------------------------------------------------- events
    def _on_reply(self, c: _VClient, rep: ApiReply, now: float) -> None:
        if rep.req_id != c.rid:
            return  # stale (pre-reconnect) reply
        if rep.kind in ("reply", "conf") and rep.success:
            c.acked += 1
            c.lats.append(now - c.t_sent)
            if self.think > 0:
                c.park_until = now + self.think * c.rng.uniform(0.5, 1.5)
                c.state = "parked"
            else:
                self._issue(c, now)
        elif rep.kind == "shed":
            c.shed += 1
            hint = max(rep.retry_after_ms, 1) / 1e3
            c.park_until = now + hint * c.rng.uniform(0.5, 1.5)
            c.state = "parked"
        elif rep.kind == "redirect":
            # rotate: against a proxy tier this is "pick another proxy"
            c.reconnects += 1
            self._connect(c, now)
            if c.state == "connecting":
                self._issue(c, now)
        else:
            self._issue(c, now)  # error reply: move on

    def _readable(self, c: _VClient, now: float) -> None:
        try:
            data = c.sock.recv(1 << 16)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            data = b""
        if not data:
            c.reconnects += 1
            self._connect(c, now)
            if c.state == "connecting":
                self._issue(c, now)
            return
        c.buf += data
        while True:
            if len(c.buf) < 8:
                break
            n = _LEN.unpack_from(c.buf, 0)[0]
            if len(c.buf) < 8 + n:
                break
            body = bytes(c.buf[8:8 + n])
            del c.buf[:8 + n]
            try:
                rep = wirecodec.decode_body(body)
            except Exception:
                continue
            if isinstance(rep, ApiReply):
                self._on_reply(c, rep, now)
                if c.sock is None or c.state != "serving":
                    break

    def _writable(self, c: _VClient, now: float) -> None:
        if c.state == "connecting":
            err = c.sock.getsockopt(
                socket.SOL_SOCKET, socket.SO_ERROR
            )
            if err:
                c.reconnects += 1
                c.park_until = now + 0.2
                self._close(c)
                c.state = "parked"
                return
            # a staggered first op (think mode) parks until its slot
            c.state = "parked" if (
                self.think > 0 and c.rid == 0
            ) else "serving"
        if c.out:
            try:
                sent = c.sock.send(c.out)
                c.out = c.out[sent:]
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                c.reconnects += 1
                self._connect(c, now)
                if c.state == "connecting":
                    self._issue(c, now)
                return
        self._want_write(c)

    # -------------------------------------------------------------- run
    def run(self) -> Dict[str, Any]:
        t0 = time.monotonic()
        t_end = t0 + self.secs
        for c in self.clients:
            self._connect(c, t0)
            if c.state != "connecting":
                continue
            if self.think > 0:
                # stagger first ops across the think window: all
                # connections come up now (the concurrency target), but
                # a synchronized 10k-op volley at t0 would measure the
                # ramp, not the steady closed loop
                c.park_until = t0 + c.rng.uniform(0.0, self.think)
            else:
                self._issue(c, t0)
        next_sweep = t0
        while True:
            now = time.monotonic()
            if now >= t_end:
                break
            for key, mask in self.sel.select(timeout=0.05):
                c: _VClient = key.data
                now = time.monotonic()
                if mask & selectors.EVENT_WRITE:
                    self._writable(c, now)
                if c.sock is not None and (mask & selectors.EVENT_READ):
                    self._readable(c, now)
            if now >= next_sweep:
                next_sweep = now + 0.25
                live = 0
                est = 0
                for c in self.clients:
                    if c.state == "parked" and now >= c.park_until:
                        if c.sock is None:
                            self._connect(c, now)
                            if c.state == "connecting":
                                self._issue(c, now)
                        else:
                            c.state = "serving"
                            self._issue(c, now)
                    elif c.state in ("serving", "connecting") \
                            and now > c.deadline:
                        c.timeouts += 1
                        c.reconnects += 1
                        self._connect(c, now)
                        if c.state == "connecting":
                            self._issue(c, now)
                    if c.sock is not None and c.state in (
                        "serving", "parked", "connecting",
                    ):
                        # live = an actual socket fd exists (serving,
                        # parked-with-connection through a backoff, or
                        # a connect in flight); a sock-less parked
                        # client is a FAILED connect and must not count
                        # toward the concurrency claim
                        live += 1
                        if c.state != "connecting":
                            est += 1  # handshake actually completed
                self.connected_peak = max(self.connected_peak, live)
                if now - t0 >= min(10.0, self.secs * 0.5):
                    # capped at half the run so short runs still record
                    # a floor instead of reporting 0 concurrency
                    # past the ramp: track the established-connection
                    # floor (half-open connects deliberately excluded)
                    self.connected_min = (
                        est if self.connected_min is None
                        else min(self.connected_min, est)
                    )
        for c in self.clients:
            self._close(c)
        self.sel.close()
        lats = sorted(
            x for c in self.clients for x in c.lats
        )
        dt = time.monotonic() - t0

        def pct(q: float) -> float:
            if not lats:
                return 0.0
            return lats[min(len(lats) - 1, int(len(lats) * q))]

        return {
            "clients": len(self.clients),
            "connected_peak": self.connected_peak,
            "connected_min": (
                self.connected_min if self.connected_min is not None
                else 0
            ),
            "secs": round(dt, 3),
            "issued": sum(c.issued for c in self.clients),
            "acked": sum(c.acked for c in self.clients),
            "shed": sum(c.shed for c in self.clients),
            "timeouts": sum(c.timeouts for c in self.clients),
            "reconnects": sum(c.reconnects for c in self.clients),
            "tput": round(sum(c.acked for c in self.clients) / dt, 2),
            "lat_p50_ms": round(pct(0.50) * 1e3, 3),
            "lat_p99_ms": round(pct(0.99) * 1e3, 3),
        }


def run_fleet(
    addrs: Sequence[Tuple[str, int]],
    clients: int,
    secs: float,
    *,
    put_ratio: float = 0.5,
    value_size: int = 64,
    num_keys: int = 64,
    seed: int = 1,
    op_timeout: float = 5.0,
    id_base: int = FLEET_ID_BASE,
    plan=None,
    think: float = 0.0,
    codec: Optional[bool] = None,
) -> Dict[str, Any]:
    """Run ``clients`` multiplexed closed-loop clients against ``addrs``
    for ``secs`` on THIS thread (callers wanting parallel pickling run
    several of these in subprocess workers, each with a disjoint
    ``id_base``).  ``plan`` (a WorkloadPlan) swaps the uniform op mix
    for per-client seeded opstreams."""
    raise_nofile(clients + 64)
    vcs = [
        _VClient(
            i, id_base + i, seed,
            stream=plan.opstream(i % max(1, plan.clients))
            if plan is not None else None,
        )
        for i in range(int(clients))
    ]
    worker = MuxWorker(
        addrs, vcs, secs,
        put_ratio=put_ratio, value_size=value_size,
        num_keys=num_keys, op_timeout=op_timeout, think=think,
        codec=codec,
    )
    return worker.run()
