"""External-system comparison adapters: ZooKeeper and etcd.

Parity: reference ``summerset_client/src/zookeeper/session.rs:16-29`` and
``summerset_client/src/etcd/kvclient.rs:12-25`` — thin KV session
wrappers exposing the same get/put surface as the native endpoint so the
bench/tester clients can run unmodified against an external system
(launched by the user; the adapters only speak the client protocol).

Gating: the Python client libraries (``kazoo`` for ZooKeeper, ``etcd3``
or ``grpc`` for etcd) are not part of the pinned environment — the
adapters import them lazily and raise a clear error when absent, so the
rest of the framework carries no dependency.  Command mapping (key ->
znode path, value encoding, sync-on-get / stale-read options) is pure
and unit-testable without a live server.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..host.statemach import Command, CommandResult
from ..utils.errors import SummersetError


def zk_path(prefix: str, key: str) -> str:
    """Key -> znode path (reference session.rs keeps a flat namespace
    under one chroot-style prefix)."""
    safe = key.replace("/", "_")
    return f"{prefix.rstrip('/')}/{safe}"


def encode_value(value: str) -> bytes:
    return value.encode("utf-8")


def decode_value(raw: Optional[bytes]) -> Optional[str]:
    return None if raw is None else raw.decode("utf-8", errors="replace")


class ZooKeeperSession:
    """ZooKeeper KV adapter (parity: zookeeper/session.rs).

    ``sync_on_get``: issue a sync() before reads for linearizable reads
    (the reference's ``sync_on_get`` option; without it ZK reads may be
    stale — the exact asymmetry the comparison benches measure).
    """

    def __init__(self, servers: str, prefix: str = "/summerset",
                 sync_on_get: bool = False, timeout: float = 15.0):
        try:
            from kazoo.client import KazooClient  # type: ignore
        except ImportError as e:
            raise SummersetError(
                "ZooKeeper adapter needs the 'kazoo' client library "
                "(not part of this environment): pip install kazoo"
            ) from e
        self.prefix = prefix
        self.sync_on_get = sync_on_get
        self.zk = KazooClient(hosts=servers, timeout=timeout)
        self.zk.start(timeout=timeout)
        self.zk.ensure_path(prefix)

    def do_cmd(self, cmd: Command) -> CommandResult:
        path = zk_path(self.prefix, cmd.key)
        if cmd.kind == "get":
            if self.sync_on_get:
                self.zk.sync(path)
            if self.zk.exists(path) is None:
                return CommandResult("get", value=None)
            raw, _ = self.zk.get(path)
            return CommandResult("get", value=decode_value(raw))
        old = None
        if self.zk.exists(path) is None:
            self.zk.create(path, encode_value(cmd.value))
        else:
            raw, _ = self.zk.get(path)
            old = decode_value(raw)
            self.zk.set(path, encode_value(cmd.value))
        return CommandResult("put", old_value=old)

    def leave(self) -> None:
        self.zk.stop()
        self.zk.close()


class EtcdKvClient:
    """etcd v3 KV adapter (parity: etcd/kvclient.rs).

    ``stale_reads``: serve reads at serializable (any-member) consistency
    instead of linearizable — the reference's ``stale_reads`` option.
    """

    def __init__(self, endpoint: Tuple[str, int],
                 stale_reads: bool = False, timeout: float = 15.0):
        try:
            import etcd3  # type: ignore
        except ImportError as e:
            raise SummersetError(
                "etcd adapter needs the 'etcd3' client library "
                "(not part of this environment): pip install etcd3"
            ) from e
        self.stale = stale_reads
        self.cli = etcd3.client(
            host=endpoint[0], port=endpoint[1], timeout=timeout
        )

    def do_cmd(self, cmd: Command) -> CommandResult:
        if cmd.kind == "get":
            raw, _ = self.cli.get(
                cmd.key, serializable=self.stale
            )
            return CommandResult("get", value=decode_value(raw))
        old_raw, _ = self.cli.get(cmd.key)
        self.cli.put(cmd.key, encode_value(cmd.value))
        return CommandResult("put", old_value=decode_value(old_raw))

    def leave(self) -> None:
        self.cli.close()
