"""graftlint: static verification of the kernel SPI contract + host lint.

Four passes, one committed baseline (``LINT.json``), one CI tier
(``ci.sh`` tier 2e → ``scripts/graftlint.py --check``):

- :mod:`.contract` — the kernel-contract verifier: every registered
  :class:`~summerset_tpu.core.protocol.ProtocolKernel` is traced at a
  small static geometry and checked against the machine-readable
  ``KERNEL_CONTRACT`` rules (state/outbox geometry and dtypes, durable
  declarations, jaxpr purity, scan-carry stability, telemetry write
  path).
- :mod:`.ranges` — the value-range prover: an inductive interval
  abstract interpretation over each kernel's state leaves (widening to
  fixpoint, narrowing, coinductive tightening, octagon-lite pairwise
  facts), serialized into LINT.json and cross-validated by the model
  checker; author-declared ``RANGE_CLAIMS`` violations are ``R2``.
- :mod:`.taint` — the flags-taint pass: a dataflow walk over the step
  jaxpr proving every inbox read that lands in state passed a
  ``flags``-derived gate; the range pass's invariants decide
  state-entangled gate polarity; intentional flows are declared per
  kernel in ``TAINT_ALLOW``.
- :mod:`.hostlint` — AST concurrency lint over ``host/``, ``manager/``,
  ``utils/``: lock-held blocking calls, non-daemon threads, wallclock /
  unseeded RNG in seeded-determinism scopes, fsync outside StorageHub,
  exception-swallowing handlers in hub threads.

The paper-side motivation (PAPERS.md): protocol-parallel optimization
porting (arxiv 1905.10786) only works when the shared substrate contract
is *checkable*, and compartmentalized SMR (arxiv 2012.15762) multiplies
the number of independently evolving components that can silently break
it.
"""

from .contract import verify_kernel  # noqa: F401
from .hostlint import lint_host  # noqa: F401
from .ranges import (  # noqa: F401
    RangeAnalysis,
    analyze_kernel_ranges,
    verify_kernel_ranges,
)
from .report import (  # noqa: F401
    Finding,
    PassResult,
    assemble_report,
    dumps_report,
)
from .taint import verify_kernel_taint  # noqa: F401
