"""Host-plane concurrency lint: AST rules over the threaded host stack.

The host planes (``summerset_tpu/host/``, ``manager/``, ``utils/``) are
hand-threaded: hub worker threads, per-peer messengers, an asyncio API
front end, seeded nemesis schedule generation.  Four recurring hazards
have each bitten a replicated-state-machine codebase at some point, and
all four are mechanically checkable:

- **H101 lock-held blocking call** — ``fsync``/socket ops/untimed
  ``queue.get`` inside a ``with <lock>:`` body serialize unrelated
  threads behind device latency (and can deadlock against the logger /
  messenger threads).
- **H102 non-daemon thread** — a forgotten ``daemon=True`` turns every
  crash-teardown path into a hang: the interpreter waits on a thread
  parked in a blocking read.
- **H103 wallclock/unseeded RNG in a seeded-determinism scope** — the
  nemesis repro contract is "same seed, byte-identical schedule";
  ``time.time()`` or an unseeded RNG inside schedule generation breaks
  it silently.  The rule also covers the tracing plane through
  ``MONOTONIC_SCOPES``, a *scoped* allow (not a blanket inline waiver):
  ``host/tracing.py`` may read the monotonic clock family for its
  stamps, but a wallclock read there still fires — wallclock jumps
  would reorder exported spans.
- **H104 fsync outside StorageHub** — durability points belong to the
  logger thread (single-writer discipline + fault injection + fsync
  telemetry); a stray ``os.fsync`` bypasses all three.
- **H106 exception swallowed in a hub thread** — a broad ``except
  Exception:`` (or bare ``except:``) whose handler neither re-raises,
  nor records a typed flight/telemetry event, nor even reads the bound
  exception, inside the hub-thread modules (server / transport /
  storage / external / ingress).  Hub worker loops MUST wrap their
  bodies to survive poison input — but a handler that drops the
  exception on the floor turns every future bug in that loop into a
  silent stall: the thread keeps spinning, the operator sees nothing.
  The contract is "survive AND record": re-raise, or emit through the
  flight recorder / telemetry counters (``pf_*``/``note_*`` helpers,
  ``.record``/``.bump``/``.inc``), or at minimum consume the exception
  value into some sink the operator can read.
- **H105 unfenced egress in the pipelined tick loop** — the pipelined
  loop's durability contract is that no vote/ack computed by step N
  leaves the process (peer tick frame OR client reply) before step N's
  WAL records are fsynced.  The fence is ``_fence_wait``; this rule
  makes the contract machine-checked: every ``send_tick`` /
  ``send_replies`` call site in the fence owner module
  (``host/server.py``) must either be dominated by a ``_fence_wait()``
  call earlier in the same function's straight-line body, or pass the
  fence down as a ``fence=..._fence_wait`` keyword so the egress seam
  itself re-checks.  The serial loop's call site carries an inline
  waiver instead (its strict stage order — fsync at the END of tick
  N-1, frames computed by step N-1 leaving at the TOP of tick N — IS
  the fence), so every egress site is either dominated or reasoned.

Suppressions are explicit, inline, and carry a reason::

    with self._wlocks[peer]:  # graftlint: disable=H101 -- per-socket writer serialization IS the lock's job
        sock.sendall(buf)

A trailing comment attaches to its own line; standalone comment lines
attach to the next statement (several can stack above one site) and the
enclosing ``with`` line is also consulted.  Suppressed findings still
appear in ``LINT.json`` (with their reason) so the baseline records
every waiver.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Tuple

from .report import Finding, PassResult

# directories scanned, relative to the package root
SCAN_DIRS = ("host", "manager", "utils")

# the one module allowed to own durability points
STORAGE_OWNER = "host/storage.py"

# seeded-determinism scopes: module -> class names whose methods must be
# wallclock-free and draw only from explicitly seeded RNGs (the nemesis
# and workload schedule-generation surfaces; NemesisRunner's and the
# open-loop drivers' wall pacing are exempt by not being listed).
# host/ingress.py (the serving-plane proxy tier) is in the H101-H104
# scan like every host/ module but declares NO seeded scope: the proxy
# holds no schedule generators — its only time reads are wall pacing
# (forward-cycle ticks, probe deadlines), which the contract exempts.
SEEDED_SCOPES: Dict[str, Tuple[str, ...]] = {
    "host/nemesis.py": ("FaultPlan", "FaultEvent"),
    "host/workload.py": ("WorkloadPlan", "WorkloadPhase", "OpStream"),
    # the autopilot's DECISION core: same seed + same senses sequence
    # must yield a byte-identical decision timeline/digest, so the
    # policy's notion of time is the evaluate-round counter, never a
    # clock.  AutopilotDriver (the wall-clock scrape/actuate loop) is
    # exempt by not being listed, like NemesisRunner.
    "host/autopilot.py": ("AutopilotPolicy", "Decision",
                          "ActuatorState"),
}

# monotonic-only scopes: module -> class names (or "*" for the whole
# module) whose timestamps must come from the monotonic clock family.
# This is a SCOPED allow, not a blanket waiver: the tracing plane's
# time.monotonic() stamps are the sanctioned path, while a wallclock
# read (time.time / datetime.now) in the same scope still fires H103 —
# wallclock can jump (NTP step, suspend) and would reorder recorded
# spans, silently corrupting exported timelines.
MONOTONIC_SCOPES: Dict[str, Tuple[str, ...]] = {
    "host/tracing.py": ("*",),
    # graftprof timing: perf_counter (monotonic family) is the
    # sanctioned stopwatch; a wallclock read in the profiler would make
    # committed PROFILE.json numbers jump with NTP steps
    "host/profiling.py": ("*",),
}

# wallclock spellings that fire inside BOTH scope kinds (the seeded
# scopes additionally ban the monotonic family — schedules must be a
# pure function of the seed, not of any clock)
WALLCLOCK_READS = (
    "time.time", "time.time_ns", "datetime.now", "datetime.utcnow",
    "datetime.datetime.now", "datetime.datetime.utcnow",
)

# call names considered blocking when made while a lock is held.
# send_msg_sync/recv_msg_sync are this repo's own blocking frame helpers
# (utils/safetcp.py) — project-aware linting catches the call sites a
# generic socket list would miss.
BLOCKING_NAMES = frozenset({
    "fsync", "fdatasync", "sleep", "accept", "connect", "recv",
    "recvfrom", "recv_into", "sendall", "send_msg_sync", "recv_msg_sync",
    "recv_exact", "sendmsg", "sendmsg_all",
})
# blocking only without a timeout= kwarg (queue.get, thread.join)
TIMEOUT_GATED_NAMES = frozenset({"get", "join"})

# H106: modules whose classes run hub worker threads (long-lived loops
# draining queues/sockets).  Broad excepts there must re-raise or
# record — a swallowed exception stalls the loop's users silently.
HUB_MODULES = frozenset({
    "host/server.py", "host/transport.py", "host/storage.py",
    "host/external.py", "host/ingress.py",
})
# call spellings that count as "recording" the failure: the flight-
# recorder/print helpers and the telemetry-counter surface
H106_RECORD_CALLS = frozenset({"record", "bump", "inc", "exception"})
H106_RECORD_PREFIXES = ("pf_", "note_", "log_")

# H105: the durability-fence owner module and its egress seams.  Egress
# calls here must be fence-dominated (a `_fence_wait()` earlier in the
# same function's straight-line body) or carry a `fence=` kwarg naming
# the fence — anything else can leak a not-yet-durable vote/ack.
FENCE_OWNER = "host/server.py"
FENCE_EGRESS_NAMES = frozenset({"send_tick", "send_replies"})
FENCE_WAIT_NAME = "_fence_wait"

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Z]\d+)(?:\s*--\s*(.*))?"
)


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def _dotted(node) -> str:
    """Best-effort dotted rendering of a Name/Attribute chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _has_kw(node: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in node.keywords)


def _broad_except(t) -> bool:
    """Is this handler type a catch-(almost)-everything?  Bare
    ``except:``, ``Exception``/``BaseException``, or a tuple containing
    one of them."""
    if t is None:
        return True
    if isinstance(t, ast.Tuple):
        return any(_broad_except(e) for e in t.elts)
    return _dotted(t) in ("Exception", "BaseException")


def _handler_records(h: ast.ExceptHandler) -> bool:
    """Does a broad handler discharge its H106 obligation?  True when
    the body re-raises, calls a recording helper
    (:data:`H106_RECORD_CALLS` / :data:`H106_RECORD_PREFIXES`), or at
    least *reads* the bound exception value (feeding it into any sink
    an operator can inspect)."""
    for stmt in h.body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                cn = _call_name(n)
                if cn and (cn in H106_RECORD_CALLS
                           or cn.startswith(H106_RECORD_PREFIXES)):
                    return True
            if (h.name and isinstance(n, ast.Name) and n.id == h.name
                    and isinstance(n.ctx, ast.Load)):
                return True
    return False


# 'lock' as its own word-start in the identifier (optionally r/w
# prefixed): `_lock`, `self._wlocks[peer]`, `rlock`, `cv_lock` — but NOT
# `block`/`_block`/`nonblocking`, where 'lock' is a substring of another
# word
_LOCK_NAME_RE = re.compile(r"(?:^|_)[rw]?lock", re.IGNORECASE)


def _looks_like_lock(expr) -> bool:
    """A with-item that names a lock: any Name/Attribute/Subscript chain
    whose final identifier matches :data:`_LOCK_NAME_RE`, or an explicit
    ``.acquire()``."""
    if isinstance(expr, ast.Call):
        if isinstance(expr.func, ast.Attribute) and \
                expr.func.attr == "acquire":
            return True
        return False
    if isinstance(expr, ast.Subscript):
        expr = expr.value
    name = ""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    return _LOCK_NAME_RE.search(name) is not None


class _Scanner(ast.NodeVisitor):
    def __init__(self, rel: str,
                 suppress: Dict[int, List[Tuple[str, str]]]):
        self.rel = rel
        self.suppress = suppress
        self.findings: List[Finding] = []
        self.suppressed: List[Tuple[Finding, str]] = []
        self._scope: List[str] = []  # class/function qualname stack
        self._lock_lines: List[int] = []  # enclosing with-lock linenos
        self._seeded_classes = SEEDED_SCOPES.get(rel, ())
        self._mono_classes = MONOTONIC_SCOPES.get(rel, ())
        # H105 dominance: per enclosing function, the linenos of
        # STRAIGHT-LINE (top-level-of-body) `..._fence_wait()` call
        # statements — a fence inside an `if` doesn't dominate
        self._fence_lines: List[List[int]] = []
        # H106: per-qualname ordinal of broad excepts, so the scope
        # symbol (`qual:except#k`) is stable across line-number churn
        self._h106_ord: Dict[str, int] = {}

    # ---------------------------------------------------------- helpers
    def _qual(self) -> str:
        return ".".join(self._scope) or "<module>"

    def _emit(self, code: str, scope_sym: str, message: str,
              line: int) -> None:
        f = Finding(code, self.rel, scope_sym, message, line=line)
        for cand in (line, *self._lock_lines[::-1]):
            for hcode, reason in self.suppress.get(cand, ()):
                if hcode == code:
                    self.suppressed.append(
                        (f, reason or "(no reason given)")
                    )
                    return
        self.findings.append(f)

    def _in_seeded_scope(self) -> bool:
        return bool(self._scope) and self._scope[0] in self._seeded_classes

    def _in_mono_scope(self) -> bool:
        if "*" in self._mono_classes:
            return True
        return bool(self._scope) and self._scope[0] in self._mono_classes

    # ------------------------------------------------------- structure
    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def _visit_func(self, node) -> None:
        self._scope.append(node.name)
        # H105 dominance set: fence waits that are top-level statements
        # of THIS function's body (straight-line — unconditionally
        # executed before anything below them)
        fences = []
        for stmt in node.body:
            if (
                isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Call)
                and _call_name(stmt.value) == FENCE_WAIT_NAME
            ):
                fences.append(stmt.lineno)
        self._fence_lines.append(fences)
        self.generic_visit(node)
        self._fence_lines.pop()
        self._scope.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if self.rel in HUB_MODULES and _broad_except(node.type):
            qual = self._qual()
            k = self._h106_ord.get(qual, 0)
            self._h106_ord[qual] = k + 1
            if not _handler_records(node):
                spelled = "bare except:" if node.type is None else \
                    f"except {_dotted(node.type) or '...'}"
                self._emit(
                    "H106", f"{qual}:except#{k}",
                    f"{spelled} in a hub-thread module neither "
                    "re-raises, records a flight/telemetry event, nor "
                    "reads the exception — a future bug in this loop "
                    "becomes a silent stall (survive AND record)",
                    node.lineno,
                )
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        is_lock = any(
            _looks_like_lock(item.context_expr) for item in node.items
        )
        if is_lock:
            self._lock_lines.append(node.lineno)
        self.generic_visit(node)
        if is_lock:
            self._lock_lines.pop()

    # ----------------------------------------------------------- rules
    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)
        dotted = _dotted(node.func)
        qual = self._qual()

        if self._lock_lines:
            if name in BLOCKING_NAMES:
                self._emit(
                    "H101", f"{qual}:{name}",
                    f"blocking call {dotted or name}() inside a "
                    "lock-held region (serializes threads behind I/O; "
                    "deadlock-prone against hub worker threads)",
                    node.lineno,
                )
            elif name in TIMEOUT_GATED_NAMES and not _has_kw(
                node, "timeout"
            ) and not node.args:
                # .get()/.join() with positional args (dict.get(k),
                # str.join(xs)) are not the queue/thread idiom
                self._emit(
                    "H101", f"{qual}:{name}",
                    f"untimed {dotted or name}() inside a lock-held "
                    "region (unbounded wait while holding the lock)",
                    node.lineno,
                )

        if name == "Thread" and dotted in ("threading.Thread", "Thread"):
            daemon_true = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            if not daemon_true:
                self._emit(
                    "H102", f"{qual}:Thread",
                    "threading.Thread(...) without daemon=True — a "
                    "crashed owner leaves the interpreter hanging on "
                    "this thread at teardown",
                    node.lineno,
                )

        if self._in_mono_scope() and dotted in WALLCLOCK_READS:
            self._emit(
                "H103", f"{qual}:{dotted}",
                f"wallclock read {dotted}() inside a monotonic-stamp "
                "tracing scope — flight-recorder/span stamps must come "
                "from the monotonic clock family (wallclock jumps "
                "reorder exported spans)",
                node.lineno,
            )

        if self._in_seeded_scope():
            if dotted in WALLCLOCK_READS + (
                "time.monotonic", "time.monotonic_ns",
                "time.perf_counter", "time.perf_counter_ns",
            ):
                self._emit(
                    "H103", f"{qual}:{dotted}",
                    f"wallclock read {dotted}() inside seeded-"
                    "determinism scope (schedules must be a pure "
                    "function of the seed)",
                    node.lineno,
                )
            elif dotted in ("random.Random", "np.random.default_rng",
                            "numpy.random.default_rng") and not (
                node.args or node.keywords
            ):
                self._emit(
                    "H103", f"{qual}:{dotted}",
                    f"unseeded RNG {dotted}() inside seeded-"
                    "determinism scope",
                    node.lineno,
                )
            elif dotted.startswith("random.") and dotted not in (
                "random.Random",
            ):
                self._emit(
                    "H103", f"{qual}:{dotted}",
                    f"module-level {dotted}() draws from the global "
                    "(unseeded) RNG inside seeded-determinism scope",
                    node.lineno,
                )

        if name in FENCE_EGRESS_NAMES and self.rel == FENCE_OWNER:
            fenced_kwarg = any(
                kw.arg == "fence"
                and _dotted(kw.value).endswith(FENCE_WAIT_NAME)
                for kw in node.keywords
            )
            dominated = bool(self._fence_lines) and any(
                ln < node.lineno for ln in self._fence_lines[-1]
            )
            if not (fenced_kwarg or dominated):
                self._emit(
                    "H105", f"{qual}:{name}",
                    f"egress call {dotted or name}() not dominated by a "
                    f"{FENCE_WAIT_NAME}() in this function's straight-"
                    "line body and not passing fence= — a vote/ack "
                    "computed by the in-flight step could leave before "
                    "its WAL records are fsynced (the pipelined loop's "
                    "durability fence contract)",
                    node.lineno,
                )

        if dotted in ("os.fsync", "os.fdatasync") and \
                self.rel != STORAGE_OWNER:
            self._emit(
                "H104", f"{qual}:{dotted}",
                f"direct {dotted}() outside StorageHub "
                f"({STORAGE_OWNER}) — durability points belong to the "
                "logger thread (single-writer + fault injection + "
                "fsync telemetry)",
                node.lineno,
            )

        self.generic_visit(node)


def _collect_suppressions(src: str) -> Dict[int, List[Tuple[str, str]]]:
    """Map line -> [(code, reason), ...].  A trailing comment attaches
    to its own line; a standalone comment line attaches to the next
    *statement* line — blank and comment-only lines in between are
    skipped, so several standalone waivers can stack above one site
    without the earlier ones landing on the later comments.  A line can
    accumulate several codes (its own trailing comment plus standalone
    ones above)."""
    out: Dict[int, List[Tuple[str, str]]] = {}
    lines = src.splitlines()
    for i, line in enumerate(lines, start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        target = i
        if line.strip().startswith("#"):
            target = i + 1
            while target <= len(lines) and (
                not lines[target - 1].strip()
                or lines[target - 1].strip().startswith("#")
            ):
                target += 1
        out.setdefault(target, []).append(
            (m.group(1), (m.group(2) or "").strip())
        )
    return out


def scan_file(path: str, rel: str) -> Tuple[List[Finding],
                                            List[Tuple[Finding, str]]]:
    with open(path, "r") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    scanner = _Scanner(rel, _collect_suppressions(src))
    scanner.visit(tree)
    return scanner.findings, scanner.suppressed


def lint_host(package_root: str) -> Tuple[PassResult, int]:
    """Scan the host-plane dirs under ``package_root`` (the
    ``summerset_tpu`` package directory).  Returns (result, files)."""
    res = PassResult()
    n_files = 0
    for d in SCAN_DIRS:
        dpath = os.path.join(package_root, d)
        if not os.path.isdir(dpath):
            continue
        for root, dirs, files in os.walk(dpath):
            # recurse so a future subpackage can't silently escape the
            # lint; deterministic order keeps LINT.json byte-stable
            dirs[:] = sorted(
                x for x in dirs if x != "__pycache__"
            )
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(root, fn)
                rel = os.path.relpath(path, package_root).replace(
                    os.sep, "/"
                )
                n_files += 1
                try:
                    findings, suppressed = scan_file(path, rel)
                except SyntaxError as e:
                    res.findings.append(Finding(
                        "H100", rel, "parse", f"unparseable: {e}"
                    ))
                    continue
                res.findings.extend(findings)
                res.suppressed.extend(suppressed)
    return res, n_files
