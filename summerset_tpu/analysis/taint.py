"""Flags-taint pass: prove every inbox read is gated on ``flags``.

The netmodel's whole masking design (``core/netmodel.py``) rests on one
consumer-side obligation: data lanes of a dead / partitioned / dropped
link still carry bytes — only the uint32 ``flags`` pair-field is zeroed
— so a kernel that folds an inbox lane into its state without first
passing it through a ``flags``-derived gate consumes garbage exactly
when the network misbehaves.  A violation is invisible to happy-path
tests and only a lucky nemesis seed would catch it; this pass proves the
property statically instead.

Mechanics: an abstract interpretation of the traced step jaxpr.  Each
variable carries

- ``sources`` — the set of inbox leaf names whose values reached it
  WITHOUT passing a gate, and
- ``guard``  — whether the value is (transitively) derived from the
  ``flags`` leaf.

Default transfer: union the sources, OR the guards.  Gates *clear* the
data operands' sources:

- ``select_n(pred, a, b)`` with a guarded ``pred`` — the classic
  ``jnp.where(ok, inbox_lane, fallback)`` shape;
- ``mul``/``and``/``or`` with a guarded operand — mask-multiply and
  bitmask gating (``flags & BIT``, ``valid & cond``, masked sums).

``cond``/``scan``/``while``/``pjit`` sub-jaxprs are walked recursively
(loop carries to fixpoint).  A state or effects output whose
``sources`` is non-empty is an unguarded read: a ``T1`` finding per
(inbox leaf, sink) flow — effects sinks are named ``effects.<leaf>``
(the host serves them to clients, so garbage there is as consumed as
garbage in state).  Intentional flows are declared per kernel in
``ProtocolKernel.TAINT_ALLOW`` with a reason — suppressions are
explicit, and stale entries (declared but no longer occurring) are
themselves ``T9`` findings so the allowlist can't rot.

Sinks are state leaves, ``effects.<leaf>`` outputs (the host serves
effects to clients), AND ``outbox.<leaf>`` lanes: an ungated
inbox->outbox flow is a relay hop putting possibly-dead-link bytes back
on the wire, and the receiver's own flags gate only attests ITS inbound
link was alive — not that the relayed payload was valid — so garbage
from a partition one hop upstream would otherwise transit a clean
forwarder invisibly.  (The chain_rep/simple_push relay lanes need no
allow entries: both forward from their flags-gated window STATE —
store-and-forward where the store is the gate — which this pass now
verifies rather than assumes.)

Polarity (closes the ROADMAP polarity-insensitivity debt): every
abstract value carries, besides ``sources``/``guard``, its ``dead``-
world class — the value it takes in the all-links-dead world where the
netmodel has zeroed ``flags``: a concrete number (``0`` for the flags
leaf and anything arithmetically forced to zero), ``"nz"`` (known
nonzero of unknown magnitude), or ``None`` (unknown).  The class is
propagated through comparisons, ``~``/``not``, bit ops
(``and``/``or``/``xor``), mask-multiplies, selects, and the structural/
reduction primitives gates actually flow through.  Wherever the
polarity IS tracked the gate rules are strict: a ``select_n`` whose
flags-derived predicate is dead-world *zero* clears only the branches
selected when the link is ALIVE — the dead-selected branch's sources
survive — so an inverted gate like ``jnp.where(valid, 0, inbox_lane)``
(which hands the lane to the dead-link case) no longer launders taint,
and a provably-inverted mask (``~valid & lane``, dead-world nonzero)
clears nothing.  State-entangled predicates (``tick_bal >
s["prep_pbal"]`` — deciding them needs runtime invariants like ballot
nonnegativity) are closed by the range pass (``analysis/ranges.py``):
every abstract value also carries a dead-world *interval* ``rng``,
state input leaves are seeded with the proven inductive invariants
(sound: an invariant holds at every reachable state, and the dead
world is a reachable state with ``flags`` zeroed — state leaves keep
their values), the shared interval transfer table propagates them,
and a comparison whose operand intervals decide its sign gets a sound
dead-world polarity the flat ``dead`` lattice cannot see.  Every gate
that drops live taint is counted: *proven* when its polarity was
decided (dead class or interval), *optimistic* when the legacy
clearing fired undecided — and each optimistic clear is reported as a
residual descriptor (primitive, trace name stack, operand avals,
sources).  Over the proven set the pass is a proof; the residual list
is the complete, checked-in statement of what is still lint.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, FrozenSet, List, Set, Tuple

try:  # jax >= 0.4.33 public spelling
    from jax.extend.core import Literal as _Literal
except ImportError:  # pragma: no cover - older jax
    from jax.core import Literal as _Literal

from .contract import (
    build_kernel, collective_variant_differs, host_variant_differs,
    rule_finding, trace_step,
)
from .report import PassResult
from . import ranges as _ranges

EMPTY: FrozenSet[str] = frozenset()


@dataclasses.dataclass(frozen=True)
class Taint:
    sources: FrozenSet[str] = EMPTY
    guard: bool = False
    # dead-world value class: what this value is in the all-links-dead
    # world (the netmodel zeroed every flags element).  A concrete
    # number means "provably equal to this", "nz" means "provably
    # nonzero, magnitude unknown", None means unknown.  Gates clear
    # taint ONLY when the polarity is tracked (see module docstring).
    dead: Any = None
    # dead-world value *interval* ``(lo, hi)`` or None when untracked:
    # state leaves are seeded with the range pass's proven inductive
    # invariants, ``ranges.prim_intervals`` propagates them, and the
    # polarity predicates (`_dead_zero`/`_dead_nonzero`) consult them —
    # the channel that decides state-entangled gates.  Joins are FLAT
    # (agreement keeps, disagreement -> None), keeping the loop-carry
    # lattice finite like ``dead``.
    rng: Any = None


CLEAN = Taint()
GUARD = Taint(EMPTY, True, 0, (0, 0))

# primitives whose first operand selects among the rest
_SELECT_PRIMS = frozenset({"select_n"})
# commutative mask applications: a guarded operand gates the other(s).
# ``or`` is deliberately NOT here — ``x | mask`` passes ``x`` through
# when the mask is zero, which is exactly the dead-link case.
_MASK_PRIMS = frozenset({"mul", "and"})
# comparison primitives: polarity is decided by evaluating the compare
# in the dead world when both operands' dead classes allow it
_CMP_PRIMS = frozenset({"eq", "ne", "lt", "le", "gt", "ge"})
# structural primitives that move a (uniform) value without changing it:
# the dead class passes straight through (all_gather replicates a
# uniform value across the axis — still uniform)
_PRESERVE_PRIMS = frozenset({
    "reshape", "broadcast_in_dim", "squeeze", "expand_dims", "transpose",
    "rev", "copy", "stop_gradient", "convert_element_type", "slice",
    "reduce_precision", "all_gather",
})
# reductions over a uniform dead-world value: or/and/max/min of v-with-
# itself is v; sum/prod are only pinned when the value is zero.  The
# mesh-collective reductions (the in-mesh quorum tally's segmented
# forms, core/quorum.py) obey the same algebra: pmax/pmin of a uniform
# dead-world value keep its class, psum of dead-world zeros is zero —
# so the dead-world class propagates THROUGH a collective tally and an
# ungated collective still carries its lane's taint to the sink
_REDUCE_KEEP = frozenset({
    "reduce_or", "reduce_and", "reduce_max", "reduce_min",
    "pmax", "pmin",
})
_REDUCE_ZERO = frozenset({"reduce_sum", "reduce_prod", "psum"})

# loop-carry fixpoints converge because each round joins the carry with
# its previous value (nondecreasing in a finite lattice); this cap only
# backstops analysis bugs, and hitting it is itself reported as a pass
# error rather than silently returning an under-approximation.  The
# ``dead`` component keeps the lattice finite: joins either agree (keep
# the class) or collapse to None, a height-2 chain.
_FIXPOINT_CAP = 10_000


def _dead_zero(t: Taint) -> bool:
    """Is this value provably zero in the dead world?  (`==` would let
    False/0.0 sneak through "nz" — compare the class explicitly.)
    Either channel decides: the flat class or a point interval."""
    if t.rng is not None and t.rng[0] == 0 and t.rng[1] == 0:
        return True
    return t.dead is not None and not isinstance(t.dead, str) and t.dead == 0


def _dead_nonzero(t: Taint) -> bool:
    if t.rng is not None and (t.rng[0] > 0 or t.rng[1] < 0):
        return True
    return t.dead == "nz" or (
        t.dead is not None and not isinstance(t.dead, str) and t.dead != 0
    )


def _join_dead(*deads):
    """Value join: agreement keeps the class, disagreement is unknown."""
    first = deads[0] if deads else None
    for d in deads[1:]:
        if d is None or first is None or d != first:
            return None
    return first


def _join_rng(*rngs):
    """Flat interval join: agreement keeps, disagreement is unknown
    (an interval hull would be more precise but makes the loop-carry
    lattice tall — a counter growing one slot per round would walk the
    whole dtype range before the fixpoint check fired)."""
    first = rngs[0] if rngs else None
    for r in rngs[1:]:
        if r is None or first is None or r != first:
            return None
    return first


def _join(*ts: Taint) -> Taint:
    src: Set[str] = set()
    guard = False
    for t in ts:
        src |= t.sources
        guard |= t.guard
    return Taint(frozenset(src), guard, _join_dead(*[t.dead for t in ts]),
                 _join_rng(*[t.rng for t in ts]))


def _literal_dead(v):
    """Dead-world class of a jaxpr literal: a literal is the same value
    in every world, so a uniform array pins the class exactly."""
    import numpy as np

    try:
        val = np.asarray(v.val)
    except Exception:
        return None
    if val.size == 0:
        return None
    u = np.unique(val)
    if len(u) != 1:
        return None
    x = u[0].item()
    if isinstance(x, bool):
        return int(x)
    if isinstance(x, (int, float)) and x == x:  # not NaN
        return x
    return None


def _cmp_dead(name: str, da, db):
    """Evaluate a comparison in the dead world, or None if undecidable.
    ``"nz"`` operands only decide equality against a concrete zero."""
    import operator as op

    fns = {"eq": op.eq, "ne": op.ne, "lt": op.lt, "le": op.le,
           "gt": op.gt, "ge": op.ge}
    if da is None or db is None:
        return None
    a_nz, b_nz = da == "nz", db == "nz"
    if a_nz or b_nz:
        other = db if a_nz else da
        if not (a_nz and b_nz) and not isinstance(other, str) and other == 0:
            if name == "eq":
                return 0  # nonzero == 0 is False
            if name == "ne":
                return 1
        return None
    return int(fns[name](da, db))


def _sub_jaxpr(obj):
    """Normalize params entries to a (jaxpr, consts) pair if jaxpr-like."""
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner, tuple(getattr(obj, "consts", ()) or ())
    if hasattr(obj, "eqns"):
        return obj, ()
    return None


class _Walker:
    """One abstract-interpretation pass over a jaxpr forest."""

    def __init__(self):
        self.depth = 0
        # gate accounting (module docstring): descriptor-keyed sets of
        # the sources each gate dropped, split by whether the gate's
        # dead-world polarity was decided ("proven") or the legacy
        # optimistic clearing fired ("optimistic" — the residual list)
        self.gates: Dict[str, Dict[Tuple, Set[str]]] = {
            "proven": {}, "optimistic": {},
        }

    def _gate(self, kind: str, eqn, cleared) -> None:
        """Record one gate occurrence that dropped live taint.  Keyed by
        a line-number-free descriptor (primitive, trace name stack,
        operand avals) so the counts and residual list serialized into
        LINT.json are deterministic across regenerations."""
        if not cleared:
            return
        key = (
            eqn.primitive.name,
            str(getattr(eqn.source_info, "name_stack", "")),
            tuple(str(v.aval) for v in eqn.invars),
        )
        self.gates[kind].setdefault(key, set()).update(cleared)

    def run(self, jaxpr, in_taints: List[Taint],
            const_taints: List[Taint] | None = None) -> List[Taint]:
        env: Dict[Any, Taint] = {}

        def read(v) -> Taint:
            if isinstance(v, _Literal):
                return Taint(EMPTY, False, _literal_dead(v),
                             _ranges.literal_interval(v))
            return env.get(v, CLEAN)

        def write(v, t: Taint) -> None:
            env[v] = t

        consts = const_taints or [CLEAN] * len(jaxpr.constvars)
        for v, t in zip(jaxpr.constvars, consts):
            write(v, t)
        for v, t in zip(jaxpr.invars, in_taints):
            write(v, t)

        for eqn in jaxpr.eqns:
            ins = [read(v) for v in eqn.invars]
            name = eqn.primitive.name
            outs = self._transfer(name, eqn, ins)
            for v, t in zip(eqn.outvars, outs):
                write(v, t)
        return [read(v) for v in jaxpr.outvars]

    # ------------------------------------------------------- transfer --
    def _transfer(self, name: str, eqn, ins: List[Taint]) -> List[Taint]:
        """Core transfer plus the dead-world interval overlay: any
        primitive computes the same function in every world, so the
        range pass's value-interval table is a sound transfer for the
        dead-world ``rng`` channel as-is.  The overlay only fills
        outputs whose core rule did not claim a (tighter) interval
        itself; call-like / control-flow prims return ``None`` from the
        table and keep the recursion's results."""
        outs = self._transfer_core(name, eqn, ins)
        ivs = []
        for v, t in zip(eqn.invars, ins):
            r = t.rng
            if r is None:
                r = _ranges.aval_bounds(v.aval)
            ivs.append((int(r[0]), int(r[1])))
        try:
            rngs = _ranges.prim_intervals(name, eqn, ivs)
        except Exception:  # pragma: no cover - table bug must not kill T1
            rngs = None
        if rngs:
            outs = [
                t if t.rng is not None or r is None
                else dataclasses.replace(t, rng=(int(r[0]), int(r[1])))
                for t, r in zip(outs, rngs)
            ]
        return outs

    def _transfer_core(self, name: str, eqn,
                       ins: List[Taint]) -> List[Taint]:
        n_out = len(eqn.outvars)
        if name in _SELECT_PRIMS and ins:
            pred, cases = ins[0], ins[1:]
            sel = None  # the case the DEAD world selects, if known
            if pred.guard and cases:
                if _dead_zero(pred):
                    sel = cases[0]
                elif len(cases) == 2 and _dead_nonzero(pred):
                    sel = cases[1]
                elif (pred.dead is not None
                      and not isinstance(pred.dead, str)
                      and 0 <= int(pred.dead) < len(cases)):
                    sel = cases[int(pred.dead)]
            if sel is not None:
                # polarity TRACKED: the dead world selects `sel`, so only
                # ITS sources are consumed on a dead link — the alive-
                # selected branches are cleared (that is the gate), and an
                # inverted gate keeps the lane's taint alive
                cleared: Set[str] = set()
                for c in cases:
                    if c is not sel:
                        cleared |= c.sources
                self._gate("proven", eqn, cleared - sel.sources)
                out = Taint(
                    frozenset(pred.sources | sel.sources), True, sel.dead,
                    sel.rng,
                )
            elif pred.guard:
                # flags-derived predicate neither the dead class nor the
                # proven intervals decide: the optimistic clearing
                # remains, counted and reported as a residual (module
                # docstring)
                dropped: Set[str] = set()
                for c in cases:
                    dropped |= c.sources
                self._gate("optimistic", eqn, dropped)
                out = Taint(pred.sources, True, None)
            else:
                out = _join(pred, *cases)
            return [out] * n_out
        if name in _MASK_PRIMS and len(ins) >= 2:
            # an operand is gated when some OTHER operand is flags-
            # derived and NOT provably inverted: a dead-world-zero mask
            # (`valid & data`) forces the dead case to 0 and clears; a
            # provably-inverted mask (`~valid & data`, dead-world
            # nonzero) passes the lane exactly on dead links and clears
            # nothing; unknown polarity keeps the optimistic clearing
            src: Set[str] = set()
            prv: Set[str] = set()
            opt: Set[str] = set()
            for i, t in enumerate(ins):
                gaters = [
                    o for j, o in enumerate(ins)
                    if j != i and o.guard and not _dead_nonzero(o)
                ]
                if gaters:
                    if any(_dead_zero(o) for o in gaters):
                        prv |= t.sources
                    else:
                        opt |= t.sources
                    continue
                src |= t.sources
            self._gate("proven", eqn, prv)
            self._gate("optimistic", eqn, opt)
            deads = [t.dead for t in ins]
            if any(_dead_zero(t) for t in ins):
                dead = 0  # 0 & x == 0 * x == 0
            elif all(
                d is not None and not isinstance(d, str) for d in deads
            ):
                a = 1
                for d in deads:
                    a = (a * d) if name == "mul" else (int(a) & int(d))
                dead = a
            else:
                dead = None
            return [
                Taint(frozenset(src), any(t.guard for t in ins), dead)
            ] * n_out
        if name in _CMP_PRIMS and len(ins) == 2:
            a, b = ins
            return [Taint(
                frozenset(a.sources | b.sources), a.guard or b.guard,
                _cmp_dead(name, a.dead, b.dead),
            )] * n_out
        if name == "not" and len(ins) == 1:
            t = ins[0]
            import numpy as np

            dt = getattr(eqn.outvars[0].aval, "dtype", None)
            logical = dt is not None and np.issubdtype(dt, np.bool_)
            if t.dead is None:
                dead = None
            elif t.dead == "nz":
                dead = 0 if logical else None  # ~(-1) == 0 for ints
            elif logical:
                dead = int(not t.dead)
            else:
                dead = ~int(t.dead)
            return [Taint(t.sources, t.guard, dead)] * n_out
        if name in ("or", "xor", "add", "sub", "max", "min") and ins:
            return [self._arith(name, ins)] * len(eqn.outvars)
        if name in _PRESERVE_PRIMS and len(ins) == 1:
            return [ins[0]] * n_out
        if name in _REDUCE_KEEP and len(ins) >= 1:
            t = _join(*ins)
            return [Taint(t.sources, t.guard, ins[0].dead)] * n_out
        if name in _REDUCE_ZERO and len(ins) >= 1:
            t = _join(*ins)
            dead = 0 if _dead_zero(ins[0]) else None
            return [Taint(t.sources, t.guard, dead)] * n_out
        if name in ("gather", "dynamic_slice") and ins:
            # element selection: every element shares the operand's
            # (uniform) dead class; indices contribute sources only
            t = _join(*ins)
            return [Taint(t.sources, t.guard, ins[0].dead)] * n_out
        if name in ("concatenate", "pad") and ins:
            return [_join(*ins)] * n_out
        sub = self._sub_transfer(name, eqn, ins)
        if sub is not None:
            return sub
        if not ins:
            return [CLEAN] * n_out
        t = _join(*ins)
        # unmodeled primitive: sources/guard join as before, but the
        # dead-world class is NOT claimed (claiming one could wrongly
        # clear taint downstream; dropping one only costs precision)
        return [Taint(t.sources, t.guard, None)] * n_out

    @staticmethod
    def _arith(name: str, ins: List[Taint]) -> Taint:
        """Dead-class transfer for the bit/arith ops gates flow through:
        concrete operands fold, zeros are identities for or/xor/add, a
        nonzero bit-or stays nonzero; anything else is unknown."""
        t = _join(*ins)
        deads = [i.dead for i in ins]
        conc = [
            d for d in deads if d is not None and not isinstance(d, str)
        ]
        dead = None
        if len(conc) == len(deads):
            import operator as op

            fns = {
                "or": lambda a, b: int(a) | int(b),
                "xor": lambda a, b: int(a) ^ int(b),
                "add": op.add, "sub": op.sub, "max": max, "min": min,
            }
            a = conc[0]
            for d in conc[1:]:
                a = fns[name](a, d)
            dead = a
        elif name in ("or", "xor", "add"):
            # concrete zeros are identities; a single surviving operand
            # keeps its class, and or of known-nonzeros stays nonzero
            rest = [i for i in ins if not _dead_zero(i)]
            if len(rest) == 1:
                dead = rest[0].dead
            elif name == "or" and rest and all(
                _dead_nonzero(i) for i in rest
            ):
                dead = "nz"
        return Taint(t.sources, t.guard, dead)

    def _sub_transfer(self, name: str, eqn, ins):
        params = eqn.params
        if name == "cond":
            branches = params["branches"]
            ops = ins[1:]
            outs = None
            for br in branches:
                pair = _sub_jaxpr(br)
                if pair is None:
                    continue
                j, _ = pair
                res = self.run(j, list(ops))
                outs = res if outs is None else [
                    _join(a, b) for a, b in zip(outs, res)
                ]
            if outs is None:
                return None
            # the predicate flows into every output (it chose them)
            return [_join(ins[0], t) for t in outs]
        if name == "while":
            cj = _sub_jaxpr(params["cond_jaxpr"])
            bj = _sub_jaxpr(params["body_jaxpr"])
            cn, bn = params["cond_nconsts"], params["body_nconsts"]
            carry = list(ins[cn + bn:])
            cond_consts = ins[:cn]
            body_consts = ins[cn:cn + bn]
            # run to an actual fixpoint: carry is joined with its
            # previous value each round, so it is nondecreasing in a
            # finite lattice and must converge (the cap only guards
            # against analysis bugs, not correctness)
            for _ in range(_FIXPOINT_CAP):
                nxt = self.run(bj[0], body_consts + carry)
                nxt = [_join(a, b) for a, b in zip(nxt, carry)]
                if nxt == carry:
                    break
                carry = nxt
            else:
                raise RuntimeError(
                    "taint while-loop fixpoint did not converge"
                )
            # the loop bound chooses the carried values (iteration count
            # is an implicit flow): join the cond predicate's taint into
            # every output, the same rule as the cond primitive
            if cj is not None:
                pred = self.run(cj[0], cond_consts + carry)
                pt = _join(*pred) if pred else CLEAN
                carry = [_join(pt, t) for t in carry]
            return carry
        if name == "scan":
            pair = _sub_jaxpr(params["jaxpr"])
            if pair is None:
                return None
            j, _ = pair
            nc, ncar = params["num_consts"], params["num_carry"]
            consts, carry, xs = ins[:nc], ins[nc:nc + ncar], ins[nc + ncar:]
            ys_acc = None
            for _ in range(_FIXPOINT_CAP):
                res = self.run(j, consts + carry + xs)
                new_carry = [
                    _join(a, b) for a, b in zip(res[:ncar], carry)
                ]
                ys = res[ncar:]
                ys_acc = ys if ys_acc is None else [
                    _join(a, b) for a, b in zip(ys_acc, ys)
                ]
                if new_carry == carry:
                    break
                carry = new_carry
            else:
                raise RuntimeError(
                    "taint scan fixpoint did not converge"
                )
            return carry + (ys_acc or [])
        # generic call-like primitives: pjit, closed_call, custom_jvp/vjp,
        # remat — look for a single sub-jaxpr param and inline it
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            if key in params:
                pair = _sub_jaxpr(params[key])
                if pair is not None:
                    return self.run(pair[0], list(ins))
        return None


def analyze_kernel_flows(kernel, invariants=None,
                         stats=None) -> Set[Tuple[str, str]]:
    """All ungated (inbox_leaf -> sink) flows in one traced step.

    ``invariants`` (leaf -> ``(lo, hi)``, from
    :func:`ranges.analyze_kernel_ranges`) seeds each state input leaf's
    dead-world interval — phase 3 of the range pass: sound because an
    inductive invariant holds at every reachable state and the dead
    world is a reachable state with ``flags`` zeroed, which leaves
    state values untouched.  ``stats``, when given, is merged with the
    walker's gate accounting (``"proven"``/``"optimistic"`` descriptor
    maps) so the caller can aggregate across config variants.
    """
    closed, in_paths, out_paths, _, _ = trace_step(kernel)
    inv = invariants or {}
    in_taints: List[Taint] = []
    for (idx, leaf), var in zip(in_paths, closed.jaxpr.invars):
        if idx == 1:  # inbox tree
            if leaf == "flags":
                in_taints.append(GUARD)
            else:
                in_taints.append(Taint(frozenset({leaf}), False))
        elif idx == 0 and leaf in inv:
            iv = _ranges.iv_clamp(
                (int(inv[leaf][0]), int(inv[leaf][1])),
                _ranges.aval_bounds(var.aval),
            )
            in_taints.append(Taint(EMPTY, False, None, iv))
        else:
            in_taints.append(CLEAN)
    w = _Walker()
    out_taints = w.run(
        closed.jaxpr, in_taints, [CLEAN] * len(closed.jaxpr.constvars)
    )
    if stats is not None:
        for kind, d in w.gates.items():
            tgt = stats.setdefault(kind, {})
            for key, srcs in d.items():
                tgt.setdefault(key, set()).update(srcs)
    flows: Set[Tuple[str, str]] = set()
    for (idx, leaf), taint in zip(out_paths, out_taints):
        if idx == 0:
            dst = leaf
        elif idx == 2:
            # effects are what the host serves to clients (read results,
            # lease status): garbage there is as consumed as garbage in
            # state.  Prefixed so an effects sink can't collide with the
            # state leaf of the same name in scopes / TAINT_ALLOW.
            dst = f"effects.{leaf}"
        else:
            # outbox leaves are sinks too: an ungated inbox->outbox flow
            # is a relay hop forwarding possibly-dead-link bytes, and
            # the RECEIVER's flags gate only attests its own inbound
            # link was alive — not that the relayed payload was valid —
            # so garbage from a partition one hop upstream would transit
            # a clean forwarder invisibly.  Deliberate relay lanes (the
            # chain/push store-and-forward windows) carry TAINT_ALLOW
            # entries naming the flow and why it is safe.
            dst = f"outbox.{leaf}"
        for src in taint.sources:
            flows.add((src, dst))
    return flows


def verify_kernel_taint(make_protocol, name: str,
                        use_ranges: bool = True) -> PassResult:
    """T1/T9 findings for one registered kernel (all config variants).

    ``use_ranges`` feeds the range pass's proven invariants into the
    dead-world interval channel (phase 3 — see module docstring); gate
    accounting rides into ``extra``: ``gates_proven`` /
    ``gates_optimistic`` count distinct gate descriptors that dropped
    live taint, and ``residuals`` lists every still-optimistic clear
    with its predicate shape.  A range-analysis failure (broken-kernel
    fixtures) degrades to interval-free analysis and is surfaced in
    ``extra["ranges_error"]`` rather than failing the pass.
    """
    res = PassResult()
    try:
        kernel = build_kernel(make_protocol, name)
        kernels = [kernel]
        if host_variant_differs(kernel):
            kernels.append(build_kernel(make_protocol, name, "host"))
        if collective_variant_differs(kernel):
            # the collective tally's [G, R] lane views are their own
            # taint surface: every tally-lane read must still pass the
            # per-link flags gate (core/quorum.py equivalence argument)
            kernels.append(build_kernel(make_protocol, name, "collective"))
        flows: Set[Tuple[str, str]] = set()
        stats: Dict[str, Dict[Tuple, Set[str]]] = {}
        for k in kernels:
            inv = None
            if use_ranges:
                try:
                    inv = _ranges.analyze_kernel_ranges(k).invariants
                except Exception as e:
                    res.extra["ranges_error"] = f"{type(e).__name__}: {e}"
            flows |= analyze_kernel_flows(k, invariants=inv, stats=stats)
        res.extra["gates_proven"] = len(stats.get("proven", {}))
        res.extra["gates_optimistic"] = len(stats.get("optimistic", {}))
        res.extra["residuals"] = [
            {"prim": p, "where": wh, "avals": list(av),
             "sources": sorted(srcs)}
            for (p, wh, av), srcs in sorted(
                stats.get("optimistic", {}).items()
            )
        ]
        allow = {
            (src, dst): reason
            for src, dst, reason in kernel.TAINT_ALLOW
        }
        for src, dst in sorted(flows):
            f = rule_finding(
                "T1", kernel.name, f"{src}->{dst}",
                f"inbox leaf {src!r} reaches sink {dst!r} without a "
                "flags-derived gate (garbage consumed on dead or "
                "partitioned links)",
            )
            reason = allow.get((src, dst))
            if reason is not None:
                res.suppressed.append((f, reason))
            else:
                res.findings.append(f)
        for (src, dst), _reason in sorted(allow.items()):
            if (src, dst) not in flows:
                res.findings.append(rule_finding(
                    "T9", kernel.name, f"{src}->{dst}",
                    f"stale TAINT_ALLOW entry: flow {src!r} -> {dst!r} "
                    "no longer occurs — delete the suppression",
                ))
    except Exception as e:
        res.error = f"{type(e).__name__}: {e}"
    return res
