"""Finding records + the deterministic LINT.json report format.

Every graftlint pass (contract verifier, flags-taint, host AST lint)
emits :class:`Finding` records.  Two properties make the committed
``LINT.json`` a usable CI baseline:

- **stable fingerprints** — a finding is identified by *what* it is
  (rule code, file/kernel, scope, symbol), never by *where in the file*
  it sits, so unrelated edits shifting line numbers don't churn the
  baseline; and
- **deterministic ordering** — every list in the report is sorted on the
  full record, so regenerating the file from a clean tree is
  byte-identical (the same contract NEMESIS.json digests follow).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Dict, List, Optional, Tuple

LINT_VERSION = 1


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint/verifier finding.

    ``code``    rule id (``C1``..``C9``, ``T1``/``T9``, ``H1xx``).
    ``where``   kernel name or repo-relative file path.
    ``scope``   sub-location that is stable across edits: a state/outbox
                leaf, a ``Class.method`` qualname — NOT a line number.
    ``message`` human-readable one-liner.
    ``line``    best-effort line number for console output only; excluded
                from the fingerprint and from LINT.json.
    """

    code: str
    where: str
    scope: str
    message: str
    line: int = 0

    @property
    def fingerprint(self) -> str:
        raw = "|".join((self.code, self.where, self.scope))
        return hashlib.sha256(raw.encode()).hexdigest()[:12]

    def render(self) -> str:
        loc = f"{self.where}:{self.line}" if self.line else self.where
        return f"{self.code} [{self.fingerprint}] {loc} ({self.scope}): " \
               f"{self.message}"

    def as_json(self) -> Dict[str, Any]:
        return {
            "code": self.code,
            "where": self.where,
            "scope": self.scope,
            "message": self.message,
            "fingerprint": self.fingerprint,
        }


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.code, f.where, f.scope))


def findings_json(findings: List[Finding]) -> List[Dict[str, Any]]:
    return [f.as_json() for f in sort_findings(findings)]


@dataclasses.dataclass
class PassResult:
    """Outcome of one pass over one subject (kernel or file set)."""

    findings: List[Finding] = dataclasses.field(default_factory=list)
    suppressed: List[Tuple[Finding, str]] = dataclasses.field(
        default_factory=list
    )  # (finding, reason)
    error: Optional[str] = None  # pass crashed (counts as failure)
    # pass-specific structured payload riding into LINT.json: the range
    # pass serializes its proven invariants here, the taint pass its
    # proven-vs-optimistic gate counts and residual predicates.  Must be
    # JSON-serializable, deterministic (pre-sorted lists), and is NOT
    # part of ok/fail — it is drift-gated data, not findings.
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.findings and self.error is None

    def as_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "status": "pass" if self.ok else "fail",
            "findings": findings_json(self.findings),
            "suppressed": [
                dict(f.as_json(), reason=reason)
                for f, reason in sorted(
                    self.suppressed,
                    key=lambda p: (p[0].code, p[0].where, p[0].scope),
                )
            ],
        }
        if self.error is not None:
            out["error"] = self.error
        if self.extra:
            out["extra"] = self.extra
        return out


def assemble_report(
    kernels: Dict[str, Dict[str, PassResult]],
    host: PassResult,
    host_files: int,
) -> Dict[str, Any]:
    """The LINT.json document (sorted keys, no timestamps)."""
    kdoc = {
        name: {pname: pres.as_json() for pname, pres in sorted(
            passes.items()
        )}
        for name, passes in sorted(kernels.items())
    }
    n_fail = sum(
        1 for passes in kernels.values()
        for pres in passes.values() if not pres.ok
    ) + (0 if host.ok else 1)
    return {
        "version": LINT_VERSION,
        "generated_by": "scripts/graftlint.py",
        "kernels": kdoc,
        "host": dict(host.as_json(), files_scanned=host_files),
        "summary": {
            "kernels_verified": len(kernels),
            "failing_passes": n_fail,
            "clean": n_fail == 0,
        },
    }


def dumps_report(doc: Dict[str, Any]) -> str:
    return json.dumps(doc, indent=1, sort_keys=True) + "\n"
