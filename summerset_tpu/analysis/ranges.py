"""Inductive value-range invariants over kernel state (graftproof).

The taint pass (``analysis/taint.py``) carried one documented soundness
weakening: a flags-derived predicate whose dead-world class mixes with
*state* — ``masked_bal > s["prep_pbal"]`` — got optimistic clearing,
because deciding its dead-world polarity needs runtime invariants
(ballot nonnegativity) that no pass derived.  This module derives them:
an inductive interval abstract interpretation over each kernel's state
leaves, run over the SAME traced step-jaxpr forest T1 walks.

Three phases:

1. **Init** — the leaf intervals of ``init_state`` are evaluated
   concretely, over a small seed set (init is seed-dependent: heartbeat
   counters start at seeded offsets), and unioned.
2. **Step as interval transfer** — the step jaxpr runs as an interval
   transfer function (``select_n`` joins/refines its reachable cases,
   ``cond``/``scan``/``while``/``pjit`` recurse, inbox and ControlInputs
   leaves are ⊤ within their dtype bounds) to a post-fixpoint with
   threshold widening, then bounded narrowing; the final candidate is
   re-checked inductive (``init ⊑ S`` and ``transfer(S) ⊑ S``) before
   anything is claimed.  Alongside the intervals one relational pass
   derives octagon-lite pairwise facts ``x <= y`` (elementwise, over the
   ``[G, R]`` signed bar/ballot leaves) by greatest-fixpoint candidate
   removal: start from every pair true at init, keep only pairs the
   step provably re-establishes, iterate until stable.
3. **Feed T1** — ``taint.py`` seeds each state input leaf's dead-world
   interval with the proven invariant (sound: the invariant holds at
   every reachable state, and the dead world is a reachable state with
   flags zeroed — state leaves keep their values), so a state-entangled
   comparison gets a *sound* dead-world class whenever the intervals
   decide its sign.

Abstraction contract (documented, oracle-checked): integer arithmetic
is modeled as **saturating at dtype bounds** — an abstract ``add``
computes the exact integer interval then clamps into the output dtype's
range, rather than modeling two's-complement wraparound.  Kernel
arithmetic never intentionally wraps (ballots, bars and window indices
all live far from the bounds), and the exhaustive model checker
(``models/explore.py``) cross-validates every proven invariant against
every concretely reached state, so a wrap that broke an interval claim
would fail the oracle with the leaf, interval and witness state.

``RANGE_CLAIMS`` on a kernel class declares author-asserted per-leaf
bounds; each is checked inductive under the same transfer (hold at
init, preserved by one abstract step) and a violation is an ``R2``
finding.  Derived invariants are serialized into LINT.json per
kernel × config variant (deterministic, drift-gated by ``--check``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

import numpy as np

try:  # jax >= 0.4.33 public spelling
    from jax.extend.core import Literal as _Literal
except ImportError:  # pragma: no cover - older jax
    from jax.core import Literal as _Literal

from .contract import (
    build_kernel, collective_variant_differs, host_variant_differs,
    rule_finding, trace_step,
)
from .report import PassResult

Interval = Tuple[int, int]

# symbolic finite bounds for the float avals that only broken-kernel
# fixtures produce (C8 bans floats from real step jaxprs)
_FINF = 2 ** 63

# widening rounds are bounded by the threshold ladder length; this cap
# only backstops analysis bugs, and hitting it is a hard error
_OUTER_CAP = 64
_INNER_CAP = 64
_NARROW_ROUNDS = 3

#: seeds the concrete init-interval evaluation unions over (init_state
#: is seed-dependent: heartbeat counters start at seeded offsets)
INIT_SEEDS = (0, 1, 2)

# rel-set size cap: var-ref sets grow along pass-through chains; past
# this they are dropped (sound — facts are only ever *removed*)
_REL_CAP = 64
_NO_REL = (frozenset(), frozenset())


# ------------------------------------------------------ interval algebra --
def aval_bounds(aval) -> Interval:
    """The dtype's representable range: the ⊤ element for this aval."""
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return (-_FINF, _FINF)
    dt = np.dtype(dt)
    if dt.kind == "b":
        return (0, 1)
    if dt.kind in "iu":
        info = np.iinfo(dt)
        return (int(info.min), int(info.max))
    return (-_FINF, _FINF)


def iv_clamp(iv: Interval, bounds: Interval) -> Interval:
    """Saturate an exact-integer interval into a dtype's range."""
    lo, hi = iv
    blo, bhi = bounds
    return (min(max(lo, blo), bhi), max(min(hi, bhi), blo))


def iv_join(a: Interval, b: Interval) -> Interval:
    return (min(a[0], b[0]), max(a[1], b[1]))


def iv_meet(a: Interval, b: Interval) -> Optional[Interval]:
    lo, hi = max(a[0], b[0]), min(a[1], b[1])
    return None if lo > hi else (lo, hi)


def iv_leq(a: Interval, b: Interval) -> bool:
    """a ⊑ b in the interval lattice (containment)."""
    return b[0] <= a[0] and a[1] <= b[1]


def _corners(a: Interval, b: Interval, fn) -> Interval:
    vs = [fn(x, y) for x in a for y in b]
    return (min(vs), max(vs))


def _tdiv(a: int, b: int) -> int:
    """C-style truncating division (what lax.div does on ints)."""
    q = abs(a) // abs(b)
    return q if (a >= 0) == (b >= 0) else -q


def _is_bool(aval) -> bool:
    dt = getattr(aval, "dtype", None)
    return dt is not None and np.dtype(dt).kind == "b"


def literal_interval(v) -> Optional[Interval]:
    """Interval of a jaxpr literal — min/max over the (possibly
    non-uniform) array, which is strictly more informative than the
    taint pass's uniform-only dead class."""
    try:
        val = np.asarray(v.val)
    except Exception:
        return None
    if val.size == 0 or val.dtype.kind not in "biu":
        if val.dtype.kind == "f" and val.size and np.all(np.isfinite(val)):
            return (val.min().item(), val.max().item())
        return None
    return (int(val.min()), int(val.max()))


def _axes_count(shape, axes) -> int:
    n = 1
    for a in axes:
        n *= int(shape[a])
    return max(n, 1)


def _cmp_interval(name: str, a: Interval, b: Interval) -> Interval:
    """Decide a comparison from operand intervals, else (0, 1)."""
    if name == "eq":
        if iv_meet(a, b) is None:
            return (0, 0)
        if a == b and a[0] == a[1]:
            return (1, 1)
    elif name == "ne":
        if iv_meet(a, b) is None:
            return (1, 1)
        if a == b and a[0] == a[1]:
            return (0, 0)
    elif name == "lt":
        if a[1] < b[0]:
            return (1, 1)
        if a[0] >= b[1]:
            return (0, 0)
    elif name == "le":
        if a[1] <= b[0]:
            return (1, 1)
        if a[0] > b[1]:
            return (0, 0)
    elif name == "gt":
        if a[0] > b[1]:
            return (1, 1)
        if a[1] <= b[0]:
            return (0, 0)
    elif name == "ge":
        if a[0] >= b[1]:
            return (1, 1)
        if a[1] < b[0]:
            return (0, 0)
    return (0, 1)


_CMP_NAMES = frozenset({"eq", "ne", "lt", "le", "gt", "ge"})
_SHAPE_PRIMS = frozenset({
    "reshape", "broadcast_in_dim", "squeeze", "expand_dims", "transpose",
    "rev", "copy", "stop_gradient", "slice", "reduce_precision",
    "all_gather",
})
# element-selection / element-keeping prims: output elements are drawn
# from the first operand (indices contribute no values)
_PICK_PRIMS = frozenset({"gather", "dynamic_slice"})
_REDUCE_SAME = frozenset({
    "reduce_max", "reduce_min", "reduce_or", "reduce_and", "pmax", "pmin",
})


def prim_intervals(name: str, eqn, ivs: List[Interval]
                   ) -> Optional[List[Interval]]:
    """Interval transfer for one non-control-flow primitive.

    Pure: the result depends only on the primitive, its params/avals and
    the operand intervals.  Returns ``None`` for primitives this table
    does not model (the caller falls back to dtype-⊤, which is sound).
    Every result is saturated into the output dtype's bounds (module
    docstring: the documented no-wrap abstraction).
    """
    outs = eqn.outvars
    n_out = len(outs)
    bounds = aval_bounds(outs[0].aval) if outs else (-_FINF, _FINF)

    def one(iv: Interval) -> List[Interval]:
        return [iv_clamp(iv, aval_bounds(o.aval)) for o in outs]

    if name in _SHAPE_PRIMS or name == "convert_element_type":
        return one(ivs[0]) if ivs else None
    if name in _PICK_PRIMS:
        return one(ivs[0]) if ivs else None
    if name in _REDUCE_SAME:
        # max/min/or/and over elements of one operand stay inside its
        # interval (bool or == max, bool and == min)
        return one(ivs[0]) if ivs else None
    if name in _CMP_NAMES and len(ivs) == 2:
        return one(_cmp_interval(name, ivs[0], ivs[1]))
    if name == "select_n" and len(ivs) >= 2:
        pred, cases = ivs[0], ivs[1:]
        live = [c for i, c in enumerate(cases)
                if pred[0] <= i <= pred[1]] or cases
        acc = live[0]
        for c in live[1:]:
            acc = iv_join(acc, c)
        return one(acc)
    if name == "add" and len(ivs) == 2:
        return one((ivs[0][0] + ivs[1][0], ivs[0][1] + ivs[1][1]))
    if name == "sub" and len(ivs) == 2:
        return one((ivs[0][0] - ivs[1][1], ivs[0][1] - ivs[1][0]))
    if name == "mul" and len(ivs) == 2:
        return one(_corners(ivs[0], ivs[1], lambda a, b: a * b))
    if name == "neg" and ivs:
        return one((-ivs[0][1], -ivs[0][0]))
    if name == "abs" and ivs:
        lo, hi = ivs[0]
        if lo >= 0:
            return one((lo, hi))
        if hi <= 0:
            return one((-hi, -lo))
        return one((0, max(-lo, hi)))
    if name == "sign" and ivs:
        lo, hi = ivs[0]
        return one((
            1 if lo > 0 else (0 if lo >= 0 else -1),
            -1 if hi < 0 else (0 if hi <= 0 else 1),
        ))
    if name == "max" and len(ivs) == 2:
        return one((max(ivs[0][0], ivs[1][0]), max(ivs[0][1], ivs[1][1])))
    if name == "min" and len(ivs) == 2:
        return one((min(ivs[0][0], ivs[1][0]), min(ivs[0][1], ivs[1][1])))
    if name == "clamp" and len(ivs) == 3:
        # lax.clamp(min, x, max) == min(max(x, min), max)
        lo_iv, x, hi_iv = ivs
        t = (max(x[0], lo_iv[0]), max(x[1], lo_iv[1]))
        return one((min(t[0], hi_iv[0]), min(t[1], hi_iv[1])))
    if name == "not" and ivs:
        if _is_bool(outs[0].aval):
            return one((1 - ivs[0][1], 1 - ivs[0][0]))
        return one((-ivs[0][1] - 1, -ivs[0][0] - 1))
    if name in ("and", "or", "xor") and len(ivs) == 2:
        (alo, ahi), (blo, bhi) = ivs
        if _is_bool(outs[0].aval):
            # bool: and == min, or == max, xor via {0,1} corners
            if name == "and":
                return one((min(alo, blo), min(ahi, bhi)))
            if name == "or":
                return one((max(alo, blo), max(ahi, bhi)))
            return one(_corners(ivs[0], ivs[1], lambda a, b: a ^ b))
        if alo >= 0 and blo >= 0:
            if name == "and":
                return one((0, min(ahi, bhi)))
            mask = (1 << max(ahi.bit_length(), bhi.bit_length())) - 1
            if name == "or":
                # x|y >= both operands for nonnegatives
                return one((max(alo, blo), mask))
            return one((0, mask))
        return one(bounds)
    if name == "shift_left" and len(ivs) == 2:
        (slo, shi) = ivs[1]
        slo, shi = max(slo, 0), min(max(shi, 0), 64)
        return one(_corners(ivs[0], (slo, shi), lambda x, s: x << s))
    if name == "shift_right_logical" and len(ivs) == 2:
        (xlo, xhi), (slo, shi) = ivs
        if xlo < 0:
            return one(bounds)  # bit reinterpretation of the sign bit
        slo, shi = max(slo, 0), min(max(shi, 0), 64)
        return one((xlo >> shi, xhi >> slo))
    if name == "shift_right_arithmetic" and len(ivs) == 2:
        (slo, shi) = ivs[1]
        slo, shi = max(slo, 0), min(max(shi, 0), 64)
        # x >> s is monotone in x for fixed s and monotone in s for
        # fixed x (toward 0 / -1), so corner evaluation is exact
        return one(_corners(ivs[0], (slo, shi), lambda x, s: x >> s))
    if name == "div" and len(ivs) == 2:
        (blo, bhi) = ivs[1]
        if blo <= 0 <= bhi:
            return one(bounds)  # possible division by zero
        return one(_corners(ivs[0], ivs[1], _tdiv))
    if name == "rem" and len(ivs) == 2:
        (alo, ahi), (blo, bhi) = ivs
        if blo > 0:
            # C-style remainder: |r| < divisor, sign follows dividend,
            # and |r| <= |dividend|
            return one((max(-(bhi - 1), min(alo, 0)),
                        min(bhi - 1, max(ahi, 0))))
        return one(bounds)
    if name == "population_count" and ivs:
        lo, hi = ivs[0]
        if lo >= 0:
            return one((0 if lo == 0 else 1, max(hi.bit_length(), 1)))
        return one((0, 64))
    if name == "iota":
        dim = eqn.params.get("dimension", 0)
        shape = eqn.params.get("shape", (1,))
        return one((0, max(int(shape[dim]) - 1, 0)))
    if name in ("argmax", "argmin") and ivs:
        op_shape = getattr(eqn.invars[0].aval, "shape", (1,))
        n = _axes_count(op_shape, eqn.params.get("axes", ()))
        return one((0, n - 1))
    if name in ("reduce_sum", "psum", "cumsum") and ivs:
        op_shape = getattr(eqn.invars[0].aval, "shape", (1,))
        if name == "cumsum":
            n = int(op_shape[eqn.params.get("axis", 0)])
            lo, hi = ivs[0]
            return one((min(lo, n * lo), max(hi, n * hi)))
        axes = eqn.params.get("axes")
        n = (_axes_count(op_shape, axes) if axes is not None
             else int(np.prod(op_shape)) or 1)
        return one((n * ivs[0][0] if ivs[0][0] < 0 else ivs[0][0],
                    n * ivs[0][1] if ivs[0][1] > 0 else ivs[0][1]))
    if name == "reduce_prod" and ivs:
        return one(bounds)
    if name == "dot_general" and len(ivs) == 2:
        dims = eqn.params.get("dimension_numbers")
        try:
            (lc, _), _ = dims
            n = _axes_count(getattr(eqn.invars[0].aval, "shape", (1,)), lc)
        except Exception:
            n = int(np.prod(getattr(eqn.invars[0].aval, "shape", (1,)))) or 1
        m = _corners(ivs[0], ivs[1], lambda a, b: a * b)
        return one((n * m[0] if m[0] < 0 else m[0],
                    n * m[1] if m[1] > 0 else m[1]))
    if name in ("concatenate",) and ivs:
        acc = ivs[0]
        for iv in ivs[1:]:
            acc = iv_join(acc, iv)
        return one(acc)
    if name == "pad" and len(ivs) >= 2:
        return one(iv_join(ivs[0], ivs[1]))
    if name in ("dynamic_update_slice",) and len(ivs) >= 2:
        return one(iv_join(ivs[0], ivs[1]))
    if name == "scatter" and len(ivs) >= 3:
        return one(iv_join(ivs[0], ivs[2]))
    if name == "scatter-max" and len(ivs) >= 3:
        # max-combine only ever raises scattered elements
        return one((ivs[0][0], max(ivs[0][1], ivs[2][1])))
    if name == "scatter-min" and len(ivs) >= 3:
        return one((min(ivs[0][0], ivs[2][0]), ivs[0][1]))
    if name == "scatter-add" and len(ivs) >= 3:
        n = int(np.prod(getattr(eqn.invars[2].aval, "shape", (1,)))) or 1
        ulo, uhi = ivs[2]
        return one((ivs[0][0] + min(0, n * ulo),
                    ivs[0][1] + max(0, n * uhi)))
    if name == "sort" and len(ivs) == n_out:
        # each output is a permutation of the matching input operand
        return [iv_clamp(iv, aval_bounds(o.aval))
                for iv, o in zip(ivs, outs)]
    return None


# -------------------------------------------------------------- widening --
def _thresholds(kernel) -> Tuple[List[int], List[int]]:
    """Per-kernel widening ladders: geometry-derived landmarks so a
    bound that is *actually* ``W-1`` or ``R`` stabilizes there instead
    of jumping straight to the dtype bound."""
    g, r, w = kernel.G, kernel.R, kernel.W
    his = sorted({0, 1, 2, g, r, w, w - 1, r - 1, 255, 256,
                  (1 << 8) * (w + 1), 1 << 16, 1 << 30})
    los = sorted({0, -1, -2, -r, -w, -256, -(1 << 16), -(1 << 30)})
    return los, his


def _widen(old: Interval, new: Interval, los: List[int],
           his: List[int], bounds: Interval) -> Interval:
    lo, hi = new
    if lo < old[0]:
        lo = bounds[0]
        for t in reversed(los):
            if t <= new[0]:
                lo = max(t, bounds[0])
                break
    else:
        lo = old[0]
    if hi > old[1]:
        hi2 = bounds[1]
        for t in his:
            if t >= new[1]:
                hi2 = min(t, bounds[1])
                break
        hi = hi2
    else:
        hi = old[1]
    return (lo, hi)


# --------------------------------------------------------------- walker --
def _sub_jaxpr(obj):
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    if hasattr(obj, "eqns"):
        return obj
    return None


def _call_jaxpr(eqn):
    """The single sub-jaxpr of a call-like eqn (pjit & friends)."""
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in eqn.params:
            j = _sub_jaxpr(eqn.params[key])
            if j is not None:
                return j
    return None


class _Walker:
    """One interval (+ optional relational) pass over a jaxpr forest.

    Abstract values are ``(interval, lbs, ubs)``: the value's integer
    interval plus — in relational mode — frozensets of lower/upper
    bound witnesses (pre-state leaf tokens ``"leaf:<name>"`` and
    ``(ctx, var-id)`` tokens for intermediate values), used by the
    pairwise-fact pass.  The ctx component is a fresh id per dynamic
    ``run`` invocation: jax *shares* sub-jaxpr bodies across call
    sites (every same-shape ``jnp.where`` reuses one body), so a bare
    var id would equate distinct runtime values and forge bound
    witnesses.
    """

    def __init__(self, rel: bool = False):
        self.rel = rel
        self.defs: Dict[Any, Any] = {}  # var -> defining eqn
        # inner call-jaxpr invar -> the outer operand it binds (pjit and
        # cond boundaries only: those bind the same value; loop carries
        # change per iteration and are deliberately NOT aliased).  This
        # is what lets the select_n branch refinement see through
        # `jnp.where` — a jitted function whose body invars are fresh
        # vars — and still match the predicate's comparison operands
        # against the case operands by identity.
        self.alias: Dict[Any, Any] = {}
        self._envs: List[Dict[Any, Tuple]] = []
        self._ctx: List[int] = []
        self._next_ctx = 0

    # -- env helpers -----------------------------------------------------
    def _read(self, env, v):
        if isinstance(v, _Literal):
            iv = literal_interval(v)
            if iv is None:
                iv = aval_bounds(v.aval)
            return (iv, _NO_REL[0], _NO_REL[1])
        val = env.get(v)
        if val is None:
            return (aval_bounds(v.aval), _NO_REL[0], _NO_REL[1])
        if not self.rel:
            return val
        # x <= x: the var itself witnesses both bounds, which is what
        # lets `exec' = min(exec + adv, commit_var)` relate to the
        # commit output without naming intermediate vars up front
        r = (self._ctx[-1], id(v))
        lbs, ubs = val[1], val[2]
        if len(lbs) < _REL_CAP:
            lbs = lbs | {r}
        if len(ubs) < _REL_CAP:
            ubs = ubs | {r}
        return (val[0], lbs, ubs)

    def run(self, jaxpr, in_vals: List[Tuple],
            const_vals: List[Tuple] | None = None) -> List[Tuple]:
        env: Dict[Any, Tuple] = {}
        self._envs.append(env)
        self._ctx.append(self._next_ctx)
        self._next_ctx += 1
        try:
            consts = const_vals or [
                (aval_bounds(v.aval), _NO_REL[0], _NO_REL[1])
                for v in jaxpr.constvars
            ]
            for v, t in zip(jaxpr.constvars, consts):
                env[v] = t
            for v, t in zip(jaxpr.invars, in_vals):
                env[v] = t
            for eqn in jaxpr.eqns:
                for ov in eqn.outvars:
                    self.defs[ov] = eqn
                ins = [self._read(env, v) for v in eqn.invars]
                outs = self._transfer(eqn.primitive.name, eqn, ins, env)
                for v, t in zip(eqn.outvars, outs):
                    env[v] = t
            return [self._read(env, v) for v in jaxpr.outvars]
        finally:
            self._envs.pop()
            self._ctx.pop()

    # -- predicate structure (select_n branch refinement) ----------------
    def _alias_invars(self, jaxpr, operands) -> None:
        for iv, outer in zip(jaxpr.invars, operands):
            self.alias[iv] = outer

    def _base(self, v, frames: Tuple = (), inward: bool = True):
        """Chase a var through value-preserving wrappers —
        convert/copy, call-boundary bindings, and (when ``inward``)
        pjit output-to-body hops — so `convert(x)` inside a jitted
        wrapper still matches `x` in conjunct/operand identity checks.

        ``frames`` is the stack of invar->outer-operand bindings for
        bodies entered by *descending* from a call eqn during this
        chase.  Descended bodies must use their frame (NOT
        ``self.alias``): jax shares bodies across call sites, so the
        global alias entry may belong to a different, later call of
        the same body.  ``self.alias`` is only valid for the currently
        *running* ancestor chain, which is exactly the frames-empty
        case.

        ``inward=False`` stops at the outermost stable var instead of
        hopping into call bodies whose envs have been popped — use it
        when the result's *interval* will be looked up (inward hops
        land on scope-dead vars and lose the interval); full inward
        chasing is for identity comparison only.

        An inward descent is identity-preserving only if the chase
        pops back *out* of the body through its frame: jax shares call
        bodies across sites, so *every* `where(...)`-shaped call of
        the same signature owns the same body-local vars, and two
        semantically unrelated outer values would "converge" on the
        same inner select outvar if the chase were allowed to
        terminate there.  ``pend`` records the pre-descent outer var
        for each descent still on the frames stack; a chase that
        terminates while inside a descended body returns the
        *outermost* pre-descent var instead of the body-local one."""
        pend: List[Tuple[int, Any]] = []
        for _ in range(64):
            if isinstance(v, _Literal):
                return v
            hit = None
            for i in range(len(frames) - 1, -1, -1):
                if v in frames[i]:
                    hit = (i, frames[i][v])
                    break
            if hit is not None:
                frames = frames[:hit[0]]
                while pend and pend[-1][0] >= len(frames):
                    pend.pop()
                v = hit[1]
                continue
            a = self.alias.get(v)
            if a is not None and not frames:
                v = a
                continue
            e = self.defs.get(v)
            if e is None:
                break
            p = e.primitive.name
            if p in ("convert_element_type", "copy"):
                v = e.invars[0]
                continue
            if inward and p not in ("scan", "while", "cond"):
                j = _call_jaxpr(e)
                if j is not None and len(j.outvars) == len(e.outvars):
                    pend.append((len(frames), v))
                    frames = frames + (dict(zip(j.invars, e.invars)),)
                    v = j.outvars[e.outvars.index(v)]
                    continue
            break
        return pend[0][1] if pend else v

    def _conjuncts(self, v, depth: int = 0, frames: Tuple = ()):
        """Comparison conjuncts implied true wherever ``v`` is true.

        Returns ``(conjs, pure)``: ``conjs`` is a list of
        ``(cmp_name, lhs, rhs)`` with operands already resolved
        through :meth:`_base` (so they compare by identity against a
        resolved case operand, and scope-local vars of shared bodies
        never leak out); ``pure`` is True when the chain contains no
        ``and``/``reduce_and`` — i.e. ``v`` IS the single comparison,
        so its *negation* is also a usable fact on the false branch.
        """
        if depth > 24 or isinstance(v, _Literal):
            return [], False
        for i in range(len(frames) - 1, -1, -1):
            if v in frames[i]:
                return self._conjuncts(frames[i][v], depth + 1,
                                       frames[:i])
        a = self.alias.get(v)
        if a is not None and not frames:
            return self._conjuncts(a, depth + 1)
        e = self.defs.get(v)
        if e is None:
            return [], False
        p = e.primitive.name
        if p not in ("scan", "while", "cond", "and", "reduce_and"):
            j = _call_jaxpr(e)
            if j is not None and len(j.outvars) == len(e.outvars):
                return self._conjuncts(
                    j.outvars[e.outvars.index(v)], depth + 1,
                    frames + (dict(zip(j.invars, e.invars)),)
                )
        if p == "and":
            a, _ = self._conjuncts(e.invars[0], depth + 1, frames)
            b, _ = self._conjuncts(e.invars[1], depth + 1, frames)
            return a + b, False
        if p == "reduce_and":
            a, _ = self._conjuncts(e.invars[0], depth + 1, frames)
            return a, False
        if p in ("broadcast_in_dim", "reshape", "squeeze", "expand_dims",
                 "convert_element_type", "copy"):
            return self._conjuncts(e.invars[0], depth + 1, frames)
        if p == "ne":
            # `ne(x, 0/False)` over a bool x is the identity wrapper
            # jnp inserts around predicates — chase through it
            rhs = e.invars[1]
            rhs_zero = (
                isinstance(rhs, _Literal)
                and literal_interval(rhs) == (0, 0)
            )
            if rhs_zero and _is_bool(e.invars[0].aval):
                return self._conjuncts(e.invars[0], depth + 1, frames)
            return [(p, self._base(e.invars[0], frames, inward=False),
                     self._base(e.invars[1], frames, inward=False))], True
        if p in _CMP_NAMES:
            return [(p, self._base(e.invars[0], frames, inward=False),
                     self._base(e.invars[1], frames, inward=False))], True
        return [], False

    def _iv_of(self, env, v) -> Interval:
        """Interval of a conjunct operand: it may live in an enclosing
        scope (the predicate is computed outside the jitted `where`
        wrapper the select sits in), so search the whole env stack."""
        if isinstance(v, _Literal):
            iv = literal_interval(v)
            return iv if iv is not None else aval_bounds(v.aval)
        for e in reversed(self._envs):
            if v in e:
                return e[v][0]
        return aval_bounds(v.aval)

    @staticmethod
    def _refine(civ: Interval, cmpn: str, left: bool,
                other: Interval) -> Optional[Interval]:
        """Meet a case interval with `case <cmpn> other` (or mirrored
        when the case var is the right operand)."""
        lo, hi = civ
        olo, ohi = other
        if not left:
            mirror = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le",
                      "eq": "eq", "ne": "ne"}
            cmpn = mirror[cmpn]
        if cmpn == "ge":
            lo = max(lo, olo)
        elif cmpn == "gt":
            lo = max(lo, olo + 1)
        elif cmpn == "le":
            hi = min(hi, ohi)
        elif cmpn == "lt":
            hi = min(hi, ohi - 1)
        elif cmpn == "eq":
            lo, hi = max(lo, olo), min(hi, ohi)
        return None if lo > hi else (lo, hi)

    _NEGATE = {"eq": "ne", "ne": "eq", "lt": "ge", "le": "gt",
               "gt": "le", "ge": "lt"}

    def _refined_case(self, env, eqn, idx: int, civ: Interval,
                      conjs, pure: bool) -> Optional[Interval]:
        """Refine case ``idx`` of a 2-case select by the predicate's
        conjuncts: on the true branch every conjunct holds; on the
        false branch only a *pure* single comparison can be negated."""
        case_v = self._base(eqn.invars[idx + 1])
        use = conjs
        if idx == 0:
            if not (pure and len(conjs) == 1):
                return civ
            n, a, b = conjs[0]
            use = [(self._NEGATE[n], a, b)]
        out = civ
        for (cmpn, a, b) in use:
            if cmpn == "ne":
                continue
            # operands arrive outward-resolved (their envs are live for
            # _iv_of); finish the identity match with full inward
            # chasing, which is deterministic so both sides converge
            if not isinstance(a, _Literal) and self._base(a) is case_v:
                out2 = self._refine(out, cmpn, True, self._iv_of(env, b))
            elif not isinstance(b, _Literal) and self._base(b) is case_v:
                out2 = self._refine(out, cmpn, False, self._iv_of(env, a))
            else:
                continue
            if out2 is None:
                return None  # branch unreachable under the conjuncts
            out = out2
        return out

    # -- transfer --------------------------------------------------------
    def _transfer(self, name, eqn, ins, env) -> List[Tuple]:
        n_out = len(eqn.outvars)

        def tops():
            return [(aval_bounds(o.aval), _NO_REL[0], _NO_REL[1])
                    for o in eqn.outvars]

        if name == "select_n" and len(ins) >= 2:
            piv = ins[0][0]
            cases = ins[1:]
            idxs = [i for i in range(len(cases))
                    if piv[0] <= i <= piv[1]] or list(range(len(cases)))
            conjs, pure = ([], False)
            if len(cases) == 2 and len(idxs) > 1:
                conjs, pure = self._conjuncts(eqn.invars[0])
            ivs = []
            for i in idxs:
                civ = cases[i][0]
                if len(cases) == 2 and (conjs or pure):
                    civ = self._refined_case(env, eqn, i, civ, conjs, pure)
                if civ is not None:
                    ivs.append(civ)
            if not ivs:  # every branch refined empty: fall back unrefined
                ivs = [cases[i][0] for i in idxs]
            acc = ivs[0]
            for iv in ivs[1:]:
                acc = iv_join(acc, iv)
            acc = iv_clamp(acc, aval_bounds(eqn.outvars[0].aval))
            lbs = ubs = frozenset()
            if self.rel:
                lbs = frozenset.intersection(*[cases[i][1] for i in idxs])
                ubs = frozenset.intersection(*[cases[i][2] for i in idxs])
            return [(acc, lbs, ubs)] * n_out

        sub = self._sub_transfer(name, eqn, ins)
        if sub is not None:
            return sub

        ivs = prim_intervals(name, eqn, [t[0] for t in ins])
        if ivs is None:
            return tops()
        rels = [_NO_REL] * n_out
        if self.rel:
            rels = [self._rel_transfer(name, eqn, ins)] * n_out
        return [(iv, r[0], r[1]) for iv, r in zip(ivs, rels)]

    def _rel_transfer(self, name, eqn, ins) -> Tuple[FrozenSet, FrozenSet]:
        """Bound-witness propagation for the order-preserving prims.
        Only exercised on same-shape elementwise ops — shape changes
        break the elementwise alignment the pairwise facts rely on."""
        out_shape = getattr(eqn.outvars[0].aval, "shape", None)
        shapes_ok = all(
            getattr(v.aval, "shape", None) == out_shape
            for v in eqn.invars if not isinstance(v, _Literal)
        )
        if not shapes_ok:
            return _NO_REL
        if name == "max" and len(ins) == 2:
            return (ins[0][1] | ins[1][1], ins[0][2] & ins[1][2])
        if name == "min" and len(ins) == 2:
            return (ins[0][1] & ins[1][1], ins[0][2] | ins[1][2])
        if name in ("convert_element_type", "copy") and ins:
            return (ins[0][1], ins[0][2])
        if name == "add" and len(ins) == 2:
            (aiv, albs, aubs), (biv, blbs, bubs) = ins[0], ins[1]
            lbs, ubs = frozenset(), frozenset()
            if biv[0] >= 0:
                lbs |= albs
            if biv[1] <= 0:
                ubs |= aubs
            if aiv[0] >= 0:
                lbs |= blbs
            if aiv[1] <= 0:
                ubs |= bubs
            return (lbs, ubs)
        if name == "sub" and len(ins) == 2:
            (_, albs, aubs), (biv, _, _) = ins[0], ins[1]
            lbs, ubs = frozenset(), frozenset()
            if biv[1] <= 0:
                lbs |= albs
            if biv[0] >= 0:
                ubs |= aubs
            return (lbs, ubs)
        if name == "clamp" and len(ins) == 3:
            return (ins[0][1], ins[2][2])
        return _NO_REL

    def _sub_transfer(self, name, eqn, ins) -> Optional[List[Tuple]]:
        params = eqn.params
        join = _join_vals
        if name == "cond":
            outs = None
            for br in params["branches"]:
                j = _sub_jaxpr(br)
                if j is None:
                    continue
                self._alias_invars(j, eqn.invars[1:])
                res = self.run(j, list(ins[1:]))
                outs = res if outs is None else [
                    join(a, b) for a, b in zip(outs, res)
                ]
            return outs
        if name == "while":
            bj = _sub_jaxpr(params["body_jaxpr"])
            cn, bn = params["cond_nconsts"], params["body_nconsts"]
            carry = list(ins[cn + bn:])
            body_consts = list(ins[cn:cn + bn])
            carry = self._loop_fixpoint(
                bj, body_consts, carry, n_carry=len(carry))[0]
            return carry
        if name == "scan":
            j = _sub_jaxpr(params["jaxpr"])
            if j is None:
                return None
            nc, ncar = params["num_consts"], params["num_carry"]
            consts = list(ins[:nc])
            carry = list(ins[nc:nc + ncar])
            xs = list(ins[nc + ncar:])
            carry, ys = self._loop_fixpoint(
                j, consts, carry, n_carry=ncar, xs=xs)
            return carry + ys
        j = _call_jaxpr(eqn)
        if j is not None:
            self._alias_invars(j, eqn.invars)
            return self.run(j, list(ins))
        return None

    def _loop_fixpoint(self, jaxpr, consts, carry, n_carry, xs=None):
        """Inner loop-carry fixpoint with the same threshold widening as
        the outer state fixpoint (scan carries are state-like)."""
        los = [0, -1, -256, -(1 << 30)]
        his = [0, 1, 2, 255, 256, 1 << 16, 1 << 30]
        ys_acc = None
        for it in range(_INNER_CAP):
            res = self.run(jaxpr, consts + carry + (xs or []))
            nxt = [_join_vals(a, b) for a, b in zip(res[:n_carry], carry)]
            ys = res[n_carry:]
            ys_acc = ys if ys_acc is None else [
                _join_vals(a, b) for a, b in zip(ys_acc, ys)
            ]
            if it >= 2:
                nxt = [
                    (_widen(c[0], n[0], sorted(los), his,
                            (-_FINF, _FINF)), n[1], n[2])
                    for c, n in zip(carry, nxt)
                ]
            if nxt == carry:
                return carry, (ys_acc or [])
            carry = nxt
        raise RuntimeError("range loop-carry fixpoint did not converge")


def _join_vals(a: Tuple, b: Tuple) -> Tuple:
    return (iv_join(a[0], b[0]), a[1] & b[1], a[2] & b[2])


# --------------------------------------------------------- kernel driver --
@dataclasses.dataclass(frozen=True)
class RangeAnalysis:
    """Proven inductive invariants for one kernel instance."""

    #: state leaf -> (lo, hi), inclusive, proven inductive
    invariants: Dict[str, Interval]
    #: elementwise pairwise facts (x, y) meaning x <= y, proven
    #: inductive over the [G, R] signed-int leaves
    pairs: Tuple[Tuple[str, str], ...]
    #: outer widening/narrowing rounds it took
    iterations: int

    def as_json(self) -> dict:
        return {
            "invariants": {
                k: [int(v[0]), int(v[1])]
                for k, v in sorted(self.invariants.items())
            },
            "pairs": [[a, b] for a, b in self.pairs],
            "iterations": self.iterations,
        }


# one analysis per traced surface, same key shape as
# contract._TRACE_CACHE so a graftlint run or pytest session pays once
_RANGE_CACHE: Dict[Tuple, RangeAnalysis] = {}


def _init_intervals(kernel, state_keys) -> Dict[str, Interval]:
    from ..core import telemetry

    out: Dict[str, Interval] = {}
    for seed in INIT_SEEDS:
        st = telemetry.attach(
            kernel.init_state(seed=seed), kernel.G, kernel.R
        )
        for k in state_keys:
            a = np.asarray(st[k])
            iv = (int(a.min()), int(a.max()))
            out[k] = iv if k not in out else iv_join(out[k], iv)
    return out


def _in_vals(in_paths, cur: Dict[str, Interval], closed,
             rel_seed: Optional[Dict[str, Tuple]] = None) -> List[Tuple]:
    vals = []
    for (idx, leaf), var in zip(in_paths, closed.jaxpr.invars):
        if idx == 0 and leaf in cur:
            rel = (rel_seed or {}).get(leaf, _NO_REL)
            vals.append((iv_clamp(cur[leaf], aval_bounds(var.aval)),
                         rel[0], rel[1]))
        else:
            # inbox and ControlInputs leaves: ⊤ within dtype bounds —
            # the netmodel and the host may deliver anything
            vals.append((aval_bounds(var.aval), _NO_REL[0], _NO_REL[1]))
    return vals


def _step_intervals(closed, in_paths, out_paths,
                    cur: Dict[str, Interval]) -> Dict[str, Interval]:
    w = _Walker(rel=False)
    outs = w.run(closed.jaxpr, _in_vals(in_paths, cur, closed))
    res: Dict[str, Interval] = {}
    for (idx, leaf), val in zip(out_paths, outs):
        if idx == 0:
            res[leaf] = (val[0] if leaf not in res
                         else iv_join(res[leaf], val[0]))
    return res


def analyze_kernel_ranges(kernel) -> RangeAnalysis:
    """Phase 1+2: inductive per-leaf intervals and pairwise facts."""
    key = (type(kernel), kernel.G, kernel.R, kernel.W,
           repr(getattr(kernel, "config", None)))
    hit = _RANGE_CACHE.get(key)
    if hit is not None:
        return hit

    closed, in_paths, out_paths, _, state = trace_step(kernel)
    state_keys = sorted(state.keys())
    init_iv = _init_intervals(kernel, state_keys)
    dtype_top = {
        leaf: aval_bounds(var.aval)
        for (idx, leaf), var in zip(in_paths, closed.jaxpr.invars)
        if idx == 0
    }
    los, his = _thresholds(kernel)

    cur = dict(init_iv)
    rounds = 0
    for it in range(_OUTER_CAP):
        rounds = it + 1
        step = _step_intervals(closed, in_paths, out_paths, cur)
        nxt = {
            k: iv_join(cur[k], step.get(k, cur[k])) for k in cur
        }
        if nxt == cur:
            break
        if it >= 2:
            nxt = {
                k: _widen(cur[k], nxt[k], los, his,
                          dtype_top.get(k, (-_FINF, _FINF)))
                for k in cur
            }
        cur = nxt
    else:
        raise RuntimeError(
            f"{kernel.name}: range fixpoint did not converge in "
            f"{_OUTER_CAP} rounds"
        )

    # bounded narrowing recovers precision widening overshot, then the
    # candidate is re-checked inductive before anything is claimed
    cand = dict(cur)
    for _ in range(_NARROW_ROUNDS):
        step = _step_intervals(closed, in_paths, out_paths, cand)
        nar = {
            k: iv_join(init_iv[k], step.get(k, cand[k])) for k in cand
        }
        if nar == cand:
            break
        cand = nar
        rounds += 1
    step = _step_intervals(closed, in_paths, out_paths, cand)
    inductive = all(
        iv_leq(init_iv[k], cand[k])
        and iv_leq(step.get(k, cand[k]), cand[k])
        for k in cand
    )
    final = cand if inductive else cur
    if not inductive:
        # `cur` converged as a post-fixpoint containing init, so it is
        # inductive by construction; assert the safety net anyway
        step = _step_intervals(closed, in_paths, out_paths, cur)
        if not all(
            iv_leq(init_iv[k], cur[k])
            and iv_leq(step.get(k, cur[k]), cur[k])
            for k in cur
        ):  # pragma: no cover - analysis bug guard
            raise RuntimeError(
                f"{kernel.name}: widened fixpoint failed its own "
                "inductiveness re-check"
            )

    final, t_rounds = _tighten(closed, in_paths, out_paths,
                               init_iv, final)
    rounds += t_rounds

    pairs = _pair_facts(kernel, closed, in_paths, out_paths,
                        state, final)
    res = RangeAnalysis(
        invariants=final, pairs=pairs, iterations=rounds
    )
    _RANGE_CACHE[key] = res
    return res


def _tighten(closed, in_paths, out_paths, init_iv, proven,
             max_rounds: int = 64):
    """Coinductive per-side tightening after the widening fixpoint.

    Widening can drag a self-dependent leaf to dtype-top in an early
    round (before the leaves it reads were themselves proven), and
    narrowing cannot recover it: once `dur_bar` is top, `dur_bar' =
    min(next_slot, dur_bar + lag)` stays top.  So run the dual
    direction: propose the init-derived bound on every side the
    fixpoint left strictly weaker, then repeatedly *revert* (to the
    proven bound) any side that one abstract step refutes.  On
    convergence the survivors satisfy both `init ⊑ S` (each candidate
    side contains the union-over-seeds init bound) and
    `transfer(S) ⊑ S` (the final step refuted nothing) — inductive by
    construction.
    """
    cand = {}
    for k, (plo, phi) in proven.items():
        ilo, ihi = init_iv[k]
        cand[k] = (max(plo, ilo), min(phi, ihi))
    if cand == dict(proven):
        return dict(proven), 0
    for it in range(max_rounds):
        step = _step_intervals(closed, in_paths, out_paths, cand)
        changed = False
        for k, (clo, chi) in list(cand.items()):
            slo, shi = step.get(k, cand[k])
            nlo = proven[k][0] if slo < clo else clo
            nhi = proven[k][1] if shi > chi else chi
            if (nlo, nhi) != (clo, chi):
                cand[k] = (nlo, nhi)
                changed = True
        if not changed:
            return cand, it + 1
    return dict(proven), max_rounds


def _pair_facts(kernel, closed, in_paths, out_paths, state,
                invariants) -> Tuple[Tuple[str, str], ...]:
    """Octagon-lite pairwise `x <= y` facts over the [G, R] signed-int
    leaves, by greatest-fixpoint candidate removal (module docstring)."""
    from ..core import telemetry

    gr = (kernel.G, kernel.R)
    cand_leaves = sorted(
        k for k, v in state.items()
        if getattr(v, "shape", None) == gr
        and np.dtype(getattr(v, "dtype", np.int32)).kind == "i"
    )
    if len(cand_leaves) < 2:
        return ()
    inits = []
    for seed in INIT_SEEDS:
        st = telemetry.attach(
            kernel.init_state(seed=seed), kernel.G, kernel.R
        )
        inits.append({k: np.asarray(st[k]) for k in cand_leaves})
    assumed = {
        (x, y)
        for x in cand_leaves for y in cand_leaves if x != y
        if all(bool(np.all(st[x] <= st[y])) for st in inits)
    }
    out_vars = {
        leaf: i for i, (idx, leaf) in enumerate(out_paths) if idx == 0
    }

    def closure(rel):
        c = set(rel)
        changed = True
        while changed:
            changed = False
            for (a, b) in list(c):
                for (b2, d) in list(c):
                    if b2 == b and (a, d) not in c and a != d:
                        c.add((a, d))
                        changed = True
        return c

    while assumed:
        cl = closure(assumed)
        rel_seed = {}
        for leaf in cand_leaves:
            tok = "leaf:" + leaf
            lbs = {tok} | {"leaf:" + a for (a, b) in cl if b == leaf}
            ubs = {tok} | {"leaf:" + b for (a, b) in cl if a == leaf}
            rel_seed[leaf] = (frozenset(lbs), frozenset(ubs))
        w = _Walker(rel=True)
        outs = w.run(closed.jaxpr,
                     _in_vals(in_paths, invariants, closed, rel_seed))
        ok_tokens = {("leaf:" + a, "leaf:" + b) for (a, b) in cl}

        def survives(x, y):
            ox, oy = outs[out_vars[x]], outs[out_vars[y]]
            for z in ox[2]:          # z >= x'
                for v in oy[1]:      # v <= y'
                    if z == v or (z, v) in ok_tokens:
                        return True
            return False

        kept = {(x, y) for (x, y) in assumed if survives(x, y)}
        if kept == assumed:
            break
        assumed = kept
    return tuple(sorted(assumed))


# ---------------------------------------------------------- claims / R2 --
def check_claims(kernel, analysis: RangeAnalysis) -> List[Tuple[str, str]]:
    """Inductiveness check for each author-declared RANGE_CLAIMS entry;
    returns (leaf, reason) per violated claim (R2 material)."""
    claims = getattr(kernel, "RANGE_CLAIMS", ()) or ()
    if not claims:
        return []
    closed, in_paths, out_paths, _, state = trace_step(kernel)
    init_iv = _init_intervals(kernel, sorted(state.keys()))
    bad: List[Tuple[str, str]] = []
    for leaf, lo, hi in claims:
        claim = (int(lo), int(hi))
        if leaf not in init_iv:
            bad.append((leaf, f"claimed leaf {leaf!r} is not a state leaf"))
            continue
        if not iv_leq(init_iv[leaf], claim):
            bad.append((leaf, (
                f"claim [{lo}, {hi}] does not hold at init_state: "
                f"init interval is {list(init_iv[leaf])}"
            )))
            continue
        if iv_leq(analysis.invariants[leaf], claim):
            continue  # implied by the proven invariant
        seeded = dict(analysis.invariants)
        m = iv_meet(seeded[leaf], claim)
        seeded[leaf] = m if m is not None else claim
        step = _step_intervals(closed, in_paths, out_paths, seeded)
        got = step.get(leaf, seeded[leaf])
        if not iv_leq(got, claim):
            bad.append((leaf, (
                f"claim [{lo}, {hi}] is not inductive: one abstract "
                f"step from the claimed interval reaches "
                f"{[int(got[0]), int(got[1])]}"
            )))
    return bad


# ------------------------------------------------------------ entrypoint --
def variant_analyses(make_protocol, name: str
                     ) -> List[Tuple[str, Any, RangeAnalysis]]:
    """(variant, kernel, analysis) for every config variant that
    differs — the same variant set the contract and taint passes walk."""
    kernel = build_kernel(make_protocol, name)
    out = [("device", kernel, analyze_kernel_ranges(kernel))]
    if host_variant_differs(kernel):
        k = build_kernel(make_protocol, name, "host")
        out.append(("host", k, analyze_kernel_ranges(k)))
    if collective_variant_differs(kernel):
        k = build_kernel(make_protocol, name, "collective")
        out.append(("collective", k, analyze_kernel_ranges(k)))
    return out


def verify_kernel_ranges(make_protocol, name: str) -> PassResult:
    """Range-proof pass for one registered kernel: derive the inductive
    invariants per config variant (serialized into the report extra) and
    check every RANGE_CLAIMS declaration (violations are R2 findings)."""
    res = PassResult()
    try:
        variants = {}
        seen = set()
        for vname, kernel, ra in variant_analyses(make_protocol, name):
            variants[vname] = ra.as_json()
            for leaf, reason in check_claims(kernel, ra):
                f = rule_finding(
                    "R2", kernel.name, leaf,
                    f"RANGE_CLAIMS[{leaf!r}]: {reason}",
                )
                if f.fingerprint not in seen:
                    seen.add(f.fingerprint)
                    res.findings.append(f)
        res.extra["variants"] = variants
    except Exception as e:
        res.error = f"{type(e).__name__}: {e}"
    return res
