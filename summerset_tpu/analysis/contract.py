"""Kernel-contract verifier: static checks over traced protocol kernels.

The SPI contract in ``core/protocol.py`` (``KERNEL_CONTRACT`` rules
C1–C9) used to live only in the module docstring, silently trusted by
every registered kernel and every plane stacked on top (engine freeze
masks, netmodel delivery, WAL durable records, telemetry lanes).  This
module checks it mechanically: each registered kernel is constructed at
a small static geometry, its ``init_state``/``zero_outbox`` pytrees are
inspected directly, and ``step`` is traced with ``jax.make_jaxpr`` /
``jax.eval_shape`` — no compilation, no device execution, so the whole
pass runs in seconds on a cold cache.

Checks are deliberately expressed against what the runtime actually
relies on (netmodel transposes axis 1/2 of non-broadcast lanes, the
engine freeze mask reshapes on leading ``[G, R]``, the WAL logs
``DURABLE_*`` rows, ``lax.scan`` carries the state structure) rather
than the looser prose they replaced.
"""

from __future__ import annotations

import ast
import dataclasses
import inspect
import os
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..core import telemetry
from ..core.protocol import KERNEL_CONTRACT, ProtocolKernel
from .report import Finding, PassResult

#: rule code -> short name, straight from the SPI's own table.  Both
#: kernel passes (contract + taint) mint findings through
#: :func:`rule_finding`, so a check can only emit codes the
#: ``KERNEL_CONTRACT`` table declares — the table IS consumed, not
#: parallel documentation that could drift from the checks.
CONTRACT_RULES: Dict[str, str] = {
    code: slug for code, slug, _ in KERNEL_CONTRACT
}


def rule_finding(code: str, where: str, scope: str, message: str,
                 line: int = 0) -> Finding:
    if code not in CONTRACT_RULES:
        raise KeyError(
            f"finding code {code!r} is not declared in "
            "core.protocol.KERNEL_CONTRACT — add the rule to the table "
            "before emitting it"
        )
    return Finding(code, where, scope, message, line=line)

# geometry small enough that tracing EPaxos's [G, R, R, W, R] lanes stays
# cheap, large enough that G/R/W are mutually distinct (shape checks
# can't pass by coincidence: 2 != 3 != 8)
VERIFY_G, VERIFY_R, VERIFY_W = 2, 3, 8
PROP_WIDTH = 4  # [G, P] input lanes ("gp" shape code)

# primitives that must never appear in a protocol step/init jaxpr: host
# round-trips and XLA's stateful (nondeterministic) RNG would both break
# the lockstep replay/model-check/nemesis determinism contracts
FORBIDDEN_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "host_callback_call", "infeed", "outfeed",
    "rng_uniform",
})

# explicit mesh collectives: allowed ONLY inside the quorum_tally phase
# scope (core/quorum.py) — the in-mesh tally plane is the one sanctioned
# cross-replica aggregation point; a collective anywhere else in a step
# is either a sharding leak or an ungated cross-replica read.  (The
# GSPMD-inserted collectives of the sharded engine never appear in the
# *traced* jaxpr — this rule governs hand-written lax.psum & friends,
# e.g. a future shard_map-lowered tally.)
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "pmax", "pmin", "all_gather", "all_to_all",
    "reduce_scatter", "ppermute", "pbroadcast",
})

_INPUT_SHAPES = {
    "g": lambda G, R: (G,),
    "gr": lambda G, R: (G, R),
    "grr": lambda G, R: (G, R, R),
    "gp": lambda G, R: (G, PROP_WIDTH),
}


def build_kernel(make_protocol, name: str, variant: str = "device",
                 G: int = VERIFY_G, R: int = VERIFY_R,
                 W: int = VERIFY_W) -> ProtocolKernel:
    """Construct a kernel at verification geometry.

    ``variant="device"`` is the defaults path; ``variant="host"`` flips
    the host-serving knobs the config exposes (``exec_follows_commit``
    off, leader leases on) so the serving-mode branches trace too;
    ``variant="collective"`` flips the quorum-tally transport
    (``tally="collective"``, core/quorum.py) so the collective-mode
    lane shapes and ingest views are a verified surface of their own.
    """
    probe = make_protocol(name, G, R, 64)
    cfg = getattr(probe, "config", None)
    if not dataclasses.is_dataclass(cfg):
        return make_protocol(name, G, R, W)
    overrides: Dict[str, Any] = {}
    if hasattr(cfg, "max_proposals_per_tick"):
        overrides["max_proposals_per_tick"] = min(
            cfg.max_proposals_per_tick, W // 2
        )
    if variant == "collective" and hasattr(cfg, "tally"):
        overrides["tally"] = "collective"
    if variant == "host":
        if hasattr(cfg, "exec_follows_commit"):
            overrides["exec_follows_commit"] = False
        if hasattr(cfg, "leader_leases"):
            # QL/Bodega carry their own (always-on) lease planes and
            # refuse the base MultiPaxos flag — fall back without it
            try:
                return make_protocol(
                    name, G, R, W,
                    dataclasses.replace(
                        cfg, leader_leases=True, **overrides
                    ),
                )
            except ValueError:
                pass
    cfg = dataclasses.replace(cfg, **overrides)
    return make_protocol(name, G, R, W, cfg)


def host_variant_differs(kernel: ProtocolKernel) -> bool:
    cfg = getattr(kernel, "config", None)
    return hasattr(cfg, "exec_follows_commit") or hasattr(
        cfg, "leader_leases"
    )


def collective_variant_differs(kernel: ProtocolKernel) -> bool:
    """Kernels with a quorum-tally transport knob get a third verified
    variant: the collective lane shapes + ingest views of
    ``tally="collective"`` (core/quorum.py)."""
    return (
        hasattr(getattr(kernel, "config", None), "tally")
        and bool(kernel.TALLY_LANES)
    )


def build_inputs(kernel: ProtocolKernel) -> Dict[str, Any]:
    """The step() input superset for this kernel: the base lanes every
    kernel consumes plus its declared ``EXTRA_INPUTS`` — providing the
    optional lanes makes the optional paths (conf planes, spr overrides,
    host-mode proposal lists) part of the traced surface."""
    G, R = kernel.G, kernel.R
    i32 = jnp.int32
    inputs: Dict[str, Any] = {
        "n_proposals": jnp.ones((G,), i32),
        "value_base": jnp.ones((G,), i32),
        "exec_floor": jnp.zeros((G, R), i32),
    }
    for name, code in kernel.EXTRA_INPUTS:
        if code not in _INPUT_SHAPES:
            raise ValueError(
                f"{type(kernel).__name__}.EXTRA_INPUTS: unknown shape "
                f"code {code!r} for {name!r}"
            )
        inputs[name] = jnp.zeros(_INPUT_SHAPES[code](G, R), i32)
    return inputs


# both passes (contract + taint) and both config variants trace the
# same step surface; keyed on (class, geometry, config repr) so one
# graftlint run — or one pytest session — traces each surface once
_TRACE_CACHE: Dict[Tuple, Tuple] = {}


def trace_step(kernel: ProtocolKernel):
    """(closed_jaxpr, in_paths, out_paths, out_shapes, state) for one
    abstract step.

    ``in_paths``/``out_paths`` name every flattened invar/outvar as
    ``(tree_index, leaf_name)`` — tree index 0/1/2 = state/inbox/inputs
    on the way in, state/outbox/effects on the way out.  ``state`` is the
    telemetry-attached input state the trace ran against."""
    key = (type(kernel), kernel.G, kernel.R, kernel.W,
           repr(getattr(kernel, "config", None)))
    hit = _TRACE_CACHE.get(key)
    if hit is None:
        hit = _TRACE_CACHE[key] = _trace_step(kernel)
    return hit


def _trace_step(kernel: ProtocolKernel):
    state = telemetry.attach(
        kernel.init_state(seed=0), kernel.G, kernel.R
    )
    inbox = kernel.zero_outbox()  # pair lanes are [G,R,R]: transpose-free
    inputs = build_inputs(kernel)

    def step_fn(st, ib, ins):
        return kernel.step(st, ib, ins)

    # the tally axis is bound so kernels (and broken-kernel fixtures)
    # using explicit mesh collectives — lax.psum over TALLY_AXIS, the
    # shard_map-lowered tally shape — still trace; size 1 makes the
    # collective the identity for the abstract trace
    from ..core.quorum import TALLY_AXIS

    closed, out_shape = jax.make_jaxpr(
        step_fn, axis_env=[(TALLY_AXIS, 1)], return_shape=True
    )(state, inbox, inputs)
    in_leaves = jax.tree_util.tree_flatten_with_path(
        (state, inbox, inputs)
    )[0]
    out_leaves = jax.tree_util.tree_flatten_with_path(out_shape)[0]

    def name_of(path) -> Tuple[int, str]:
        idx = path[0].idx
        keys = []
        for p in path[1:]:
            if isinstance(p, jax.tree_util.DictKey):
                keys.append(p.key)
            elif isinstance(p, jax.tree_util.GetAttrKey):
                # StepEffects is a registered dataclass: its leaves path
                # through GetAttrKey (commit_bar / exec_bar / extra[...])
                keys.append(p.name)
        return idx, keys[-1] if keys else jax.tree_util.keystr(path[1:])

    in_paths = [name_of(p) for p, _ in in_leaves]
    out_paths = [name_of(p) for p, _ in out_leaves]
    out_shapes = [sd for _, sd in out_leaves]
    return closed, in_paths, out_paths, out_shapes, state


# --------------------------------------------------------------- checks --
def _is_int_like(dtype) -> bool:
    return (
        jnp.issubdtype(dtype, jnp.integer)
        or jnp.issubdtype(dtype, jnp.bool_)
    )


def _check_state(kernel, state, out: List[Finding]) -> None:
    name = kernel.name
    G, R = kernel.G, kernel.R
    for bar in ("commit_bar", "exec_bar"):
        leaf = state.get(bar)
        if leaf is None:
            out.append(rule_finding(
                "C1", name, bar, f"required state leaf {bar!r} missing"
            ))
        elif leaf.shape != (G, R) or leaf.dtype != jnp.int32:
            out.append(rule_finding(
                "C1", name, bar,
                f"{bar} must be int32 [G, R]; got "
                f"{leaf.dtype} {leaf.shape}",
            ))
    for key, leaf in state.items():
        if leaf.ndim < 2 or leaf.shape[:2] != (G, R):
            out.append(rule_finding(
                "C1", name, key,
                f"state leaf {key!r} must lead with [G, R]=({G}, {R}); "
                f"got shape {leaf.shape}",
            ))
        if not _is_int_like(leaf.dtype):
            out.append(rule_finding(
                "C2", name, key,
                f"state leaf {key!r} has non-integer dtype {leaf.dtype} "
                "(protocol state is integer/bool only)",
            ))


def _check_outbox(kernel, outbox, out: List[Finding]) -> None:
    name = kernel.name
    G, R = kernel.G, kernel.R
    bl = kernel.broadcast_lanes
    flags = outbox.get("flags")
    if flags is None:
        out.append(rule_finding(
            "C3", name, "flags",
            "outbox must contain the uint32 [G, R, R] 'flags' pair-field "
            "(the netmodel's masking lane)",
        ))
    else:
        if flags.dtype != jnp.uint32 or flags.shape != (G, R, R):
            out.append(rule_finding(
                "C3", name, "flags",
                f"flags must be uint32 [G, R, R]; got {flags.dtype} "
                f"{flags.shape}",
            ))
        if "flags" in bl:
            out.append(rule_finding(
                "C3", name, "flags",
                "flags must be a per-pair field, not a broadcast lane",
            ))
    for lane in sorted(bl):
        if lane not in outbox:
            out.append(rule_finding(
                "C3", name, lane,
                f"broadcast_lanes entry {lane!r} is not an outbox leaf",
            ))
    for key, leaf in outbox.items():
        if not _is_int_like(leaf.dtype):
            out.append(rule_finding(
                "C4", name, key,
                f"outbox leaf {key!r} has non-integer dtype {leaf.dtype}",
            ))
        if key in bl:
            if leaf.ndim < 2 or leaf.shape[:2] != (G, R):
                out.append(rule_finding(
                    "C3", name, key,
                    f"broadcast lane {key!r} must lead with "
                    f"[G, R_src]; got shape {leaf.shape}",
                ))
        elif leaf.ndim < 3 or leaf.shape[:3] != (G, R, R):
            out.append(rule_finding(
                "C3", name, key,
                f"outbox leaf {key!r} must be per-pair "
                f"[G, R_src, R_dst, ...] or declared in broadcast_lanes; "
                f"got shape {leaf.shape}",
            ))


def _check_durable(kernel, state, out: List[Finding]) -> None:
    name = kernel.name
    G, R = kernel.G, kernel.R
    scalars, windows = kernel.DURABLE_SCALARS, kernel.DURABLE_WINDOWS
    if scalars is None or windows is None:
        out.append(rule_finding(
            "C5", name, "DURABLE",
            "kernel declares no durable acceptor contract "
            "(DURABLE_SCALARS/DURABLE_WINDOWS is None); the host refuses "
            "to serve it",
        ))
        return
    for k in scalars:
        leaf = state.get(k)
        if leaf is None:
            out.append(rule_finding(
                "C5", name, k,
                f"DURABLE_SCALARS entry {k!r} is not a state leaf",
            ))
        elif leaf.shape != (G, R):
            out.append(rule_finding(
                "C5", name, k,
                f"DURABLE_SCALARS entry {k!r} must be [G, R]; got "
                f"{leaf.shape}",
            ))
    for k in windows:
        leaf = state.get(k)
        if leaf is None:
            out.append(rule_finding(
                "C5", name, k,
                f"DURABLE_WINDOWS entry {k!r} is not a state leaf",
            ))
        elif leaf.ndim < 3 or leaf.shape[:2] != (G, R):
            out.append(rule_finding(
                "C5", name, k,
                f"DURABLE_WINDOWS entry {k!r} must lead with [G, R] and "
                f"carry a window axis; got {leaf.shape}",
            ))
    if kernel.VALUE_WINDOW not in windows:
        out.append(rule_finding(
            "C5", name, kernel.VALUE_WINDOW,
            f"VALUE_WINDOW {kernel.VALUE_WINDOW!r} must be one of "
            "DURABLE_WINDOWS (the WAL logs payload ids from it)",
        ))


def _walk_jaxprs(closed):
    """Yield every (sub-)jaxpr reachable from a ClosedJaxpr."""
    seen = set()
    stack = [closed.jaxpr]
    while stack:
        j = stack.pop()
        if id(j) in seen:
            continue
        seen.add(id(j))
        yield j
        for eqn in j.eqns:
            for v in eqn.params.values():
                vs = v if isinstance(v, (tuple, list)) else (v,)
                for item in vs:
                    inner = getattr(item, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        stack.append(inner)
                    elif hasattr(item, "eqns"):
                        stack.append(item)


def _in_tally_scope(eqn) -> bool:
    """Was this equation traced under the quorum_tally phase scope?
    The scope rides each eqn's source_info name stack (the same
    metadata graftprof's HLO attribution joins on)."""
    from ..core.quorum import TALLY_SCOPE

    stack = getattr(getattr(eqn, "source_info", None), "name_stack", None)
    return stack is not None and TALLY_SCOPE in str(stack)


def _check_purity(kernel, closed, what: str, out: List[Finding]) -> None:
    name = kernel.name
    # NamedAxisEffect is the axis BINDING a mesh collective records —
    # not host I/O; whether the collective itself is legal is decided
    # by the scope rule below, not the effects check
    real_effects = [
        e for e in closed.effects
        if type(e).__name__ != "NamedAxisEffect"
    ]
    if real_effects:
        out.append(rule_finding(
            "C6", name, what,
            f"{what} jaxpr carries effects {sorted(map(str, real_effects))}"
            " (host I/O or ordered side effects inside the kernel)",
        ))
    hit = set()
    for j in _walk_jaxprs(closed):
        for eqn in j.eqns:
            pname = eqn.primitive.name
            if pname in FORBIDDEN_PRIMITIVES and pname not in hit:
                hit.add(pname)
                out.append(rule_finding(
                    "C6", name, f"{what}:{pname}",
                    f"forbidden primitive {pname!r} in the {what} jaxpr",
                ))
            elif (
                pname in COLLECTIVE_PRIMITIVES
                and pname not in hit
                and not _in_tally_scope(eqn)
            ):
                hit.add(pname)
                out.append(rule_finding(
                    "C6", name, f"{what}:{pname}",
                    f"collective primitive {pname!r} outside the "
                    "quorum_tally phase scope — cross-replica "
                    "aggregation is sanctioned only inside the in-mesh "
                    "tally plane (core/quorum.py)",
                ))


def _check_int_discipline(kernel, closed, out: List[Finding]) -> None:
    name = kernel.name
    hit = set()
    for j in _walk_jaxprs(closed):
        for eqn in j.eqns:
            for v in eqn.outvars:
                dt = getattr(v.aval, "dtype", None)
                if dt is not None and jnp.issubdtype(dt, jnp.floating):
                    key = eqn.primitive.name
                    if key not in hit:
                        hit.add(key)
                        out.append(rule_finding(
                            "C8", name, f"step:{key}",
                            f"floating-point intermediate ({dt}) produced "
                            f"by {key!r} in the step jaxpr — protocol "
                            "lanes are integer-only",
                        ))
    return


def _check_carry(kernel, state, out_paths, out_shapes,
                 out: List[Finding]) -> None:
    """C7: step's state output must be a structurally identical carry."""
    name = kernel.name
    in_leaves = {
        k: (v.shape, jnp.dtype(v.dtype)) for k, v in state.items()
    }
    out_leaves = {}
    for (idx, leaf), sd in zip(out_paths, out_shapes):
        if idx == 0:
            out_leaves[leaf] = (sd.shape, jnp.dtype(sd.dtype))
    for k in sorted(set(in_leaves) | set(out_leaves)):
        if k not in out_leaves:
            out.append(rule_finding(
                "C7", name, k, f"state leaf {k!r} dropped by step()"
            ))
        elif k not in in_leaves:
            out.append(rule_finding(
                "C7", name, k, f"state leaf {k!r} invented by step()"
            ))
        elif in_leaves[k] != out_leaves[k]:
            out.append(rule_finding(
                "C7", name, k,
                f"state leaf {k!r} changes shape/dtype across step(): "
                f"{in_leaves[k]} -> {out_leaves[k]} (breaks the "
                "lax.scan carry)",
            ))


# --------------------------------------------------- input declarations --
#: inputs every kernel receives without declaring them (host/server.py
#: and the engine always provide these three)
BASE_INPUTS = frozenset({"n_proposals", "value_base", "exec_floor"})


def _ends_with_inputs(expr) -> bool:
    """Does this expression denote the step ``inputs`` mapping?  A bare
    ``inputs`` name, or a one-hop ``<local>.inputs`` attribute (a carry
    tuple like ``c.inputs``) — NOT deeper chains (``self.cfg.inputs``),
    which denote unrelated objects that merely share the spelling."""
    if isinstance(expr, ast.Name):
        return expr.id == "inputs"
    if isinstance(expr, ast.Attribute) and expr.attr == "inputs":
        return (
            isinstance(expr.value, ast.Name)
            and expr.value.id != "self"
        )
    return False


class _InputReadScan(ast.NodeVisitor):
    """Collect step-input name literals read off the ``inputs`` mapping:
    ``inputs["name"]`` / ``c.inputs["name"]`` subscripts and
    ``inputs.get("name")`` optional reads.  Only string literals count —
    a computed key cannot be cross-checked statically."""

    def __init__(self):
        self.reads: List[Tuple[str, int, str]] = []  # (name, line, how)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if (
            isinstance(fn, ast.Attribute) and fn.attr == "get"
            and _ends_with_inputs(fn.value) and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            self.reads.append((node.args[0].value, node.lineno, "get"))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        sl = node.slice
        if (
            _ends_with_inputs(node.value)
            and isinstance(sl, ast.Constant)
            and isinstance(sl.value, str)
        ):
            self.reads.append((sl.value, node.lineno, "subscript"))
        self.generic_visit(node)


def _check_input_declarations(kernel, out: List[Finding]) -> None:
    """C10: cross-check every input-name literal the kernel's class
    bodies read against ``BASE_INPUTS`` + its ``EXTRA_INPUTS`` table.

    Scope is the ClassDef subtree of each MRO class in its defining
    module (not the whole file: fixture/protocol modules hold several
    kernels), excluding the SPI base itself.  This closes the
    honor-system gap the trace-based checks cannot: a direct subscript
    of an undeclared input KeyErrors the trace loudly, but an optional
    ``.get()`` read silently drops its branch from the verified/tainted
    surface."""
    name = kernel.name
    declared = BASE_INPUTS | {n for n, _ in kernel.EXTRA_INPUTS}
    seen_classes = set()
    for cls in type(kernel).__mro__:
        if cls in (ProtocolKernel, object):
            continue
        mod = inspect.getmodule(cls)
        fn = getattr(mod, "__file__", None)
        if not fn or getattr(mod, "__name__", "") == \
                "summerset_tpu.core.protocol":
            continue
        key = (fn, cls.__name__)
        if key in seen_classes:
            continue
        seen_classes.add(key)
        try:
            with open(fn, "r") as f:
                tree = ast.parse(f.read(), filename=fn)
        except (OSError, SyntaxError):
            continue
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.ClassDef)
                and node.name == cls.__name__
            ):
                continue
            scan = _InputReadScan()
            scan.visit(node)
            for rname, line, how in scan.reads:
                if rname in declared:
                    continue
                out.append(rule_finding(
                    "C10", name,
                    f"{os.path.basename(fn)}:{rname}",
                    f"step-input {rname!r} read via "
                    f"{'inputs.get(...)' if how == 'get' else 'inputs[...]'}"
                    " but not declared in EXTRA_INPUTS (nor a base "
                    "input) — the traced surface silently drops this "
                    "branch",
                    line=line,
                ))


# ------------------------------------------------- telemetry write path --
class _TelemWriteScan(ast.NodeVisitor):
    """Flag direct references to the telemetry lane block in a protocol
    module: kernels must contribute via the ``_telemetry`` hook dict and
    let ``core/telemetry.accumulate`` fold it (one stacked add/max), not
    scatter into ``s["telem"]`` per lane."""

    def __init__(self):
        self.hits: List[Tuple[int, str]] = []

    def visit_Constant(self, node: ast.Constant) -> None:
        if node.value == telemetry.TELEM_KEY:
            self.hits.append((node.lineno, "literal 'telem' subscript"))

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in ("accumulate", "bump", "TELEM_KEY"):
            base = getattr(node.value, "id", None)
            if base == "telemetry":
                self.hits.append(
                    (node.lineno, f"direct telemetry.{node.attr} call")
                )
        self.generic_visit(node)


def _check_telemetry_path(kernel, out: List[Finding]) -> None:
    """C9 over the kernel's defining module(s) (its MRO inside
    ``summerset_tpu/protocols``); ``core/protocol.py`` and
    ``core/telemetry.py`` themselves ARE the sanctioned path."""
    name = kernel.name
    seen_files = set()
    for cls in type(kernel).__mro__:
        mod = inspect.getmodule(cls)
        fn = getattr(mod, "__file__", None)
        if not fn or os.sep + "protocols" + os.sep not in fn:
            continue
        if fn in seen_files:
            continue
        seen_files.add(fn)
        with open(fn, "r") as f:
            tree = ast.parse(f.read(), filename=fn)
        scan = _TelemWriteScan()
        scan.visit(tree)
        for line, what in scan.hits:
            out.append(rule_finding(
                "C9", name,
                f"{os.path.basename(fn)}:{what}",
                f"telemetry lane block touched directly ({what}) — "
                "contribute lanes via the _telemetry hook so the one "
                "stacked accumulate stays the only write path",
                line=line,
            ))


# ------------------------------------------------------------ entrypoint --
def verify_kernel(make_protocol, name: str) -> PassResult:
    """Run every contract check for one registered kernel (both config
    variants when they differ); findings are deduplicated by fingerprint."""
    res = PassResult()
    seen = set()

    def emit(findings: List[Finding]) -> None:
        for f in findings:
            # key on message too: the fingerprint identifies a *site*
            # (stable across variants), but one site can carry distinct
            # violations (e.g. flags mis-typed AND broadcast-declared)
            # that must all surface in one run
            key = (f.fingerprint, f.message)
            if key not in seen:
                seen.add(key)
                res.findings.append(f)

    try:
        kernel = build_kernel(make_protocol, name)
        variants = [kernel]
        if host_variant_differs(kernel):
            variants.append(build_kernel(make_protocol, name, "host"))
        if collective_variant_differs(kernel):
            variants.append(
                build_kernel(make_protocol, name, "collective")
            )
        for k in variants:
            found: List[Finding] = []
            plain_state = k.init_state(seed=0)
            _check_state(k, plain_state, found)
            _check_outbox(k, k.zero_outbox(), found)
            _check_durable(k, plain_state, found)
            # init_state runs eagerly on the host exactly once (concrete
            # Python like int() is fine there) — only step(), the
            # scanned/jitted hot path, is traced for purity
            closed, _, out_paths, out_shapes, state = trace_step(k)
            _check_purity(k, closed, "step", found)
            _check_int_discipline(k, closed, found)
            _check_carry(k, state, out_paths, out_shapes, found)
            emit(found)
        tel_found: List[Finding] = []
        _check_telemetry_path(kernel, tel_found)
        _check_input_declarations(kernel, tel_found)
        emit(tel_found)
    except Exception as e:  # a crash in tracing is itself a violation
        res.error = f"{type(e).__name__}: {e}"
    return res
