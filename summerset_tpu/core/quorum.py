"""Collective quorum-tally plane: consensus majority-counting as a
replica-axis reduction instead of R² pairwise message traffic.

NetPaxos ("Network Hardware-Accelerated Consensus") and "Paxos Made
Switch-y" move vote counting into the programmable switch: acceptors
emit votes, the *network* tallies them, and the coordinator reads one
aggregated result.  The TPU-mesh analog: acceptor tally records are
per-SOURCE ``[G, R]`` lanes instead of per-PAIR ``[G, R_src, R_dst]``
lanes, delivery is the broadcast-lane path (an all-gather over the
replica mesh axis when it is sharded — one collective per tick instead
of the pairwise all-to-all), and the quorum frontier falls out of a
segmented reduction over the gathered lanes
(:func:`quorum_frontier` / :func:`coverage_frontier`).

Two modes, selected by the ``tally`` field of the kernel config
(``"pairwise"`` — the default, digest-compatible with every committed
artifact — or ``"collective"``):

- **pairwise**: the accept-reply lanes (``ar_*``; RSPaxos/Crossword add
  the reconstruct-request lanes ``rq_*``) are ``[G, R, R]`` outbox
  leaves: R² int32 values enqueued through the netmodel delay line per
  lane per tick and transposed to receiver orientation on pop.
- **collective**: the same lanes are declared in
  ``ProtocolKernel.TALLY_LANES`` and shrink to per-source ``[G, R]``
  broadcast lanes — a follower's tally record (vote ballot, run start,
  durable frontier, nack hint) does not depend on the destination, so
  the pairwise fan-out carried R copies of the same value.  The
  ``flags`` pair-field still carries the ACCEPT_REPLY/nack bits per
  link, so masking (drops, partitions, pauses), the delay model's
  visibility semantics, and every receiver-side gate are EXACTLY the
  pairwise ones: the collective reads the same D-tick-delayed vote
  lanes the pairwise path would have delivered, and the equivalence
  gate (tests/test_quorum_tally.py) holds state/effects/telemetry
  byte-identical between the modes.

Phase attribution: everything tally-shaped — the netmodel's delay-line
handling of the declared tally lanes (both modes) and the kernels'
frontier reductions — runs under the ``quorum_tally`` phase scope
(:data:`PHASE_TALLY`), so graftprof's per-phase HLO/op/device-time
attribution measures the pairwise-vs-collective cost head-to-head
(PROFILE.json ``tally_sweep``, gated by scripts/perf_gate.py).

Lint surface: hand-written mesh collectives (``lax.psum`` & friends —
the shard_map lowering a future pod-scale tally may use) are permitted
by graftlint rule C6 ONLY inside the ``quorum_tally`` scope;
:data:`TALLY_AXIS` is the axis name the verifier's trace environment
binds so such kernels remain traceable.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable

import jax.numpy as jnp

from .protocol import PHASE_SCOPE_PREFIX, phase_scope

Pytree = Any

#: the phase name the tally plane is attributed to (kernels declare it
#: in PHASES; the netmodel tags tally-lane transport with it)
PHASE_TALLY = "quorum_tally"
#: full scope string as it appears in jaxpr name stacks / HLO op_name
TALLY_SCOPE = PHASE_SCOPE_PREFIX + PHASE_TALLY
#: the mesh axis name bound by the graftlint trace environment so
#: explicit in-kernel collectives (lax.psum over this axis) trace
TALLY_AXIS = "tally"

TALLY_MODES = ("pairwise", "collective")


def check_tally(mode: str) -> str:
    if mode not in TALLY_MODES:
        raise ValueError(
            f"unknown tally mode {mode!r}; pick one of {TALLY_MODES}"
        )
    return mode


def tally_scope():
    """The named scope all tally-plane work runs under (netmodel lane
    transport + kernel frontier reductions); honors the graftprof
    phase-scope ablation switch like every kernel phase."""
    return phase_scope(PHASE_TALLY)


def pair_views(
    inbox: Pytree, names: Iterable[str], collective: bool
) -> Dict[str, Any]:
    """Receiver-oriented views of the tally lanes.

    Pairwise mode: the lanes arrive transposed ``[G, R_dst, R_src]``
    and are returned as-is.  Collective mode: the lanes arrive as
    per-source ``[G, R_src]`` broadcasts and are viewed as
    ``[G, 1, R_src]`` so every receiver-side expression broadcasts over
    the destination axis unchanged.  At every position where the flags
    pair-field carries the reply bit, the two views are value-identical
    — which is the whole equivalence argument: all consumer code gates
    on flags, so the modes produce byte-identical state.
    """
    if not collective:
        return {k: inbox[k] for k in names}
    return {k: inbox[k][:, None, :] for k in names}


def source_lane(gate, value):
    """Collective-mode outbox write: one per-source ``[G, R]`` record
    (``value`` where ``gate``, else 0) replacing the pairwise
    ``jnp.where(do_send, value[..., None], 0)`` R²-fan-out."""
    return jnp.where(gate, value, 0)


# ------------------------------------------------------ segmented tallies --
_INF = jnp.int32(1 << 30)


def quorum_frontier(frontiers, k: int):
    """k-th largest cumulative frontier along the last (replica) axis —
    the accept-quorum frontier of every group in ONE segmented
    reduction: the highest slot bound that >= k replicas acked
    everything below.  Under a replica-sharded mesh the sort/reduce
    lowers to a replica-axis collective (GSPMD inserts it); this is the
    in-mesh analog of the switch's vote counter."""
    r = frontiers.shape[-1]
    return jnp.sort(frontiers, axis=-1)[..., r - k]


def coverage_frontier(cover, abs_w, need, slot_known, in_rng):
    """First absolute slot whose coverage fails — the per-slot
    (Crossword shard-coverage) quorum tally as one segmented reduction
    over ``[G, R, R_peer, W]``.

    ``cover``      [G, R, R_peer] cumulative per-peer frontiers;
    ``abs_w``      [G, R, W] absolute slots of the ring window;
    ``need``       [G, R, W] per-slot required count (assignment-width
                   dependent);
    ``slot_known`` [G, R, W] the window actually holds that slot;
    ``in_rng``     [G, R, W] slots that must pass (below the target
                   frontier).

    Returns ``[G, R]``: the minimum failing absolute slot (INF when the
    whole range passes); callers clip against their frontier bound.
    """
    cnt = (cover[..., :, None] > abs_w[..., None, :]).sum(
        axis=2, dtype=jnp.int32
    )
    fail = in_rng & ~((cnt >= need) & slot_known)
    return jnp.min(jnp.where(fail, abs_w, _INF), axis=2)
