"""In-kernel metric lanes: a small int32 telemetry block in the scan carry.

The serving story so far measured everything from the *client* side
(percentiles in TPUTLAT/HOSTBENCH) or from readiness lines parsed out of
stdout; which component saturates first — the question compartmentalized
SMR work starts from (arxiv 2012.15762) — was unanswerable for the device
plane.  This module gives every kernel a fixed set of per-replica metric
lanes accumulated *inside* the jitted tick, so a ``lax.scan`` over
thousands of ticks lands with its own measurement attached: no host
round-trips, no tracing, just one extra ``[G, R, K]`` int32 leaf in the
state pytree riding the scan carry.

Mechanics:

- ``attach(state, G, R)`` adds the ``telem`` leaf; ``Engine.init`` does
  this by default.  A state *without* the leaf compiles a telemetry-free
  kernel variant (the ablation: ``state.pop("telem")`` after init) —
  presence is a static Python condition, so the off-variant carries
  literally zero lane cost.
- Kernels contribute via the ``ProtocolKernel._telemetry`` SPI hook
  (``core/protocol.py``): a dict of lane-name -> ``[G, R]`` increments,
  folded in by ``accumulate`` — counters add, high-water lanes max.
- The network model adds the ``net_drops`` / ``net_delay_ticks`` lanes at
  ``push`` time (``core/netmodel.py``), where the loss masks and jitter
  draws actually live.
- Observability is NOT protocol state: the model-check explorer excludes
  the lane block from its dedup hash (``models/explore.py``), and nothing
  durable references it.

Host replicas scrape row ``[:, me]`` of the block — each server's
``metrics_dump`` snapshot carries its own ``[G, K]`` lane matrix.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp
import numpy as np

# lane order is the wire format of the scraped [G, K] block: append-only
# (scrapers index by name through LANE_IDX, but committed artifacts keep
# meaning across PRs only if existing indices never move)
COUNTER_LANES = (
    "commits",          # commit_bar advance (slots committed)
    "proposals",        # new slots proposed/accepted into the log
    "elections",        # campaigns started (own ballot/term raised)
    "ballots_adopted",  # foreign ballot/term adoptions
    "heartbeats",       # accepted leader heartbeats / appends
    "grants",           # lease grants held (lease-plane protocols)
    "net_drops",        # messages masked at the netmodel egress
    "net_delay_ticks",  # total jitter ticks added to enqueued sends
)
MAXGAUGE_LANES = (
    "win_occupancy_hw",  # high-water voted-window occupancy (slots)
)
LANES = COUNTER_LANES + MAXGAUGE_LANES
K = len(LANES)
LANE_IDX: Dict[str, int] = {n: i for i, n in enumerate(LANES)}
_MAX_SET = frozenset(MAXGAUGE_LANES)

TELEM_KEY = "telem"


def zero_block(num_groups: int, population: int):
    """Fresh ``[G, R, K]`` lane block (every lane zero)."""
    return jnp.zeros((num_groups, population, K), jnp.int32)


def attach(state: Dict[str, Any], num_groups: int, population: int):
    """Add the lane block to a state pytree (idempotent)."""
    if TELEM_KEY not in state:
        state[TELEM_KEY] = zero_block(num_groups, population)
    return state


def accumulate(telem, contrib: Dict[str, Any]):
    """Fold per-tick contributions into the block.

    ``contrib`` maps lane name -> ``[G, R]`` array (bool or int); counter
    lanes add, high-water lanes take the running max.  Unknown lane names
    are a bug in the contributing kernel — fail loudly.

    One stacked add over the counter sub-block and one stacked max over
    the high-water sub-block (lane order puts counters first): a
    per-lane ``at[:, :, i].add`` chain re-materializes the whole block
    once per lane, which alone cost >10% of a steady CPU tick at the
    bench shape — the two-op form is what keeps the lanes under the 5%
    ablation budget (ci.sh tier 2d).
    """
    for name in contrib:
        if name not in LANE_IDX:  # undeclared lane = contributor bug
            raise KeyError(name)
    G, R, _ = telem.shape
    zero = jnp.zeros((G, R), jnp.int32)

    def col(name):
        v = contrib.get(name)
        if v is None:
            return zero
        v = jnp.asarray(v)
        return v.astype(jnp.int32) if v.dtype != jnp.int32 else v

    nc = len(COUNTER_LANES)
    if any(n in contrib for n in COUNTER_LANES):
        add = jnp.stack([col(n) for n in COUNTER_LANES], axis=-1)
        telem = telem.at[:, :, :nc].add(add)
    if any(n in contrib for n in MAXGAUGE_LANES):
        hw = jnp.stack([col(n) for n in MAXGAUGE_LANES], axis=-1)
        telem = telem.at[:, :, nc:].max(hw)
    return telem


def bump(telem, name: str, v):
    """Fold one lane (same semantics as :func:`accumulate`)."""
    v = jnp.asarray(v)
    if v.dtype != jnp.int32:
        v = v.astype(jnp.int32)
    i = LANE_IDX[name]
    if name in _MAX_SET:
        return telem.at[:, :, i].max(v)
    return telem.at[:, :, i].add(v)


def snapshot_row(telem, me: int) -> Dict[str, Any]:
    """Host-side decode of one replica's ``[G, K]`` block: per-lane group
    totals (sum for counters, max for high-water) plus the raw per-group
    matrix when small enough to commit into artifacts."""
    block = np.asarray(telem)[:, me]  # [G, K]
    lanes = {}
    for name, i in LANE_IDX.items():
        col = block[:, i]
        lanes[name] = int(col.max() if name in _MAX_SET else col.sum())
    out: Dict[str, Any] = {"lanes": lanes}
    if block.shape[0] <= 64:
        out["per_group"] = {
            name: block[:, i].tolist() for name, i in LANE_IDX.items()
        }
    return out
