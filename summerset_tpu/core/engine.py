"""The lockstep tick engine: jitted step + delivery, scan-based batch runs.

This replaces the reference's per-replica ``tokio::select!`` event loop
(e.g. ``src/protocols/multipaxos/mod.rs:834-997``): one jitted ``tick``
advances *every* replica of *every* group by one round of
receive → protocol step → send, and ``run_ticks`` rolls the tick under
``lax.scan`` so thousands of consensus rounds execute per device dispatch.

Pause semantics (manager oracle parity): the engine freezes the entire state
of non-alive replicas each tick — protocols never see their own pause, same
as a SIGSTOP'd reference process.

Durable crash semantics: a ``reset`` mask (``ControlInputs.reset``,
scheduled by ``FaultPlan.compile_device`` as the ``device_reset`` fault
class) rebuilds the masked replicas' state rows from ONLY their kernel's
declared ``DURABLE_SCALARS``/``DURABLE_WINDOWS`` leaves at the start of
the tick — every volatile leaf is rewound to its freshly-booted
``init_state`` value.  This is the vectorized in-kernel form of the
host's crash-restart contract (``core/protocol.py``): boot
``init_state``, then ``restore_durable`` replays the WAL record — the
durable leaves ARE that record (with applier floor 0), and everything
else is exactly what a host crash loses.

Pod-scale mesh mode (``mesh=``): the same tick compiled over a 2-D
``(group, replica)`` device mesh (``core/sharding.py``).  ``init()``
places the ``[G, R, ...]`` state with ``state_sharding``, every scan
carry is re-constrained to the same specs (so GSPMD keeps placement
stable across ticks and lowers in-group netmodel delivery to the
replica-axis all-to-all), and the scan entry points **donate the
carry** (``donate_argnums``) so steady-state windows run
device-resident: the host feeds only per-window ``ControlInputs`` /
api-batch arrays and drains effects — the ``[G, R, ...]`` state never
round-trips.  Donation contract: after ``run_ticks``/``run_synthetic``
returns, the state/netstate the caller passed IN are dead buffers
(host reuse raises); hold onto the RETURNED carry only.  The
single-tick path (``tick``) never donates — serving/test loops read
the previous state between ticks.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from . import sharding as shardlib
from . import telemetry
from .netmodel import ControlInputs, NetConfig, NetModel
from .protocol import ProtocolKernel, StepEffects

Pytree = Any


class Engine:
    def __init__(
        self,
        kernel: ProtocolKernel,
        netcfg: NetConfig = NetConfig(),
        seed: int = 0,
        mesh: Optional[Any] = None,
        donate: Optional[bool] = None,
    ):
        self.kernel = kernel
        self.netcfg = netcfg
        # pod-scale mesh mode: shard the [G, R, ...] plane over a
        # (group, replica) device mesh; geometry the mesh cannot split
        # evenly is refused here with the axis named (check_mesh)
        self.mesh = mesh
        if mesh is not None:
            shardlib.check_mesh(mesh, kernel.G, kernel.R)
        # scan-carry donation defaults on exactly when sharded (the
        # device-resident steady-state contract); opt in/out explicitly
        # with donate=True/False
        self.donate = (mesh is not None) if donate is None else bool(donate)
        # Lease-plane safety is CLOCK-FREE only because a grantor's
        # countdown outlives the holder's belief by more than the maximum
        # one-way message delay (quorum_leases.py module doc;
        # leaderlease.rs:10-21): with delay > lease_margin a revocation /
        # lapsed promise can arrive at the grantor AFTER a stale holder
        # served a local read at the old conf — a linearizability hole no
        # test would catch deterministically.  Refuse the geometry here,
        # where the kernel's tick semantics meet the netmodel's delays.
        kcfg = getattr(kernel, "config", None)
        margin = getattr(kcfg, "lease_margin", None)
        leases_on = (
            getattr(kcfg, "leader_leases", False)
            or getattr(kcfg, "enable_leader_leases", False)
            or hasattr(kcfg, "lease_len")  # QL/Bodega grantor plane
        )
        if margin is not None and leases_on and (
            margin <= netcfg.max_delay_ticks
        ):
            raise ValueError(
                f"lease_margin ({margin}) must exceed the network's "
                f"max_delay_ticks ({netcfg.max_delay_ticks}): a lease "
                "margin at or below the one-way delay permits a stale "
                "holder to serve a local read after its grantor's "
                "countdown lapsed"
            )
        self.seed = seed
        self.net = NetModel(
            netcfg, kernel.G, kernel.R, kernel.broadcast_lanes,
            tally_lanes=kernel.tally_lanes,
        )
        # the freshly-booted state template a device_reset rewinds
        # volatile rows to (the host analog boots init_state before
        # restore_durable; a ServerReplica always boots seed=0, the
        # engine reuses its own seed).  Closed over by the jitted tick
        # as constants, and handed out by init() as the initial carry —
        # the template and the boot state are the SAME arrays, so no
        # second copy of the [G, R, ...] pytree is ever held.
        self._boot = self.kernel.init_state(seed)
        self._constrain = (
            partial(_constrain_carry, mesh) if mesh is not None else None
        )
        donate_kw = {"donate_argnums": (0, 1)} if self.donate else {}
        self._tick_jit = jax.jit(
            partial(_tick, self.kernel, self.net, self._boot,
                    self._constrain)
        )
        self._run_jit = jax.jit(
            partial(_run_scan, self.kernel, self.net, self._boot,
                    self._constrain),
            static_argnums=3, **donate_kw,
        )
        self._synth_jit = jax.jit(
            partial(_run_synth, self.kernel, self.net, self._boot,
                    self._constrain),
            static_argnums=(2, 3), **donate_kw,
        )

    def init(self) -> Tuple[Pytree, Pytree]:
        # share the boot template's (immutable) arrays as the initial
        # carry rather than building a second init_state
        state = dict(self._boot)
        # metric lanes ride the scan carry (core/telemetry.py); drop the
        # leaf (state.pop("telem")) to compile the lane-free ablation
        telemetry.attach(state, self.kernel.G, self.kernel.R)
        netstate = self.net.init_netstate(self.kernel.zero_outbox(), self.seed)
        if self.mesh is not None:
            # place onto the mesh.  device_put COPIES: the boot template
            # the jitted tick closes over (and hands out on a later
            # init()) survives even when this carry is later donated.
            state = shardlib.shard_pytree(self.mesh, state)
            netstate = shardlib.shard_netstate(self.mesh, netstate)
        elif self.donate:
            # mesh-less donation (explicit donate=True) needs the same
            # protection the mesh path gets from device_put: without a
            # copy the handed-out carry IS the boot template's arrays,
            # and donating it would delete the template under the jitted
            # tick's closure and every later init()
            state = {k: jnp.array(v) for k, v in state.items()}
        return state, netstate

    def tick(
        self, state: Pytree, netstate: Pytree, inputs: Dict[str, Any]
    ) -> Tuple[Pytree, Pytree, StepEffects]:
        """One lockstep tick (jitted)."""
        return self._tick_jit(state, netstate, inputs)

    def run_ticks(
        self,
        state: Pytree,
        netstate: Pytree,
        inputs_seq: Dict[str, Any],
        collect: bool = False,
    ):
        """Run T ticks under ``lax.scan``.

        ``inputs_seq`` is a dict of arrays with leading time dim T (the scan
        xs).  Returns ``(state, netstate, fxs)`` where ``fxs`` is the
        per-tick effects stacked over T when ``collect=True`` and ``None``
        otherwise (read final bars from the returned state).  Compile
        caching is by shapes, handled by jax.jit itself.

        With ``donate`` on (the sharded default) the passed-in
        state/netstate are DONATED: their buffers alias the returned
        carry and reading them from the host afterwards raises.  This is
        the per-window serving shape — the host feeds only the
        ``inputs_seq`` arrays and drains ``fxs``; the ``[G, R, ...]``
        carry never leaves the devices.
        """
        return self._run_jit(state, netstate, inputs_seq, collect)

    def run_synthetic(
        self,
        state: Pytree,
        netstate: Pytree,
        num_ticks: int,
        proposals_per_tick: int,
    ):
        """Device-only benchmark loop: saturating synthetic client load.

        Per tick every group is offered ``proposals_per_tick`` new commands
        with value ids ``tick * P + i`` — the analog of the reference's
        open-loop bench client at unlimited frequency
        (``summerset_client/src/clients/bench.rs``), minus host I/O.
        """
        return self._synth_jit(state, netstate, num_ticks, proposals_per_tick)

    # -- AOT lowering hooks (graftprof, host/profiling.py) -------------------
    # The profiler needs the XLA artifacts themselves — ``lowered
    # .compile()`` for cost_analysis / memory_analysis / compile wall
    # time, the optimized-HLO text for per-phase op attribution, and the
    # compiled executable as a warm timed callable that can never hit a
    # recompile inside a measurement window.

    def lower_tick(
        self, state: Pytree, netstate: Pytree, inputs: Dict[str, Any]
    ):
        """``jax.stages.Lowered`` for ONE tick at these shapes — the
        scan-length-free module the analytic perf gate compares."""
        return self._tick_jit.lower(state, netstate, inputs)

    def lower_synthetic(
        self,
        state: Pytree,
        netstate: Pytree,
        num_ticks: int,
        proposals_per_tick: int,
    ):
        """``jax.stages.Lowered`` for the scanned synthetic-load run —
        compile once, then call the compiled executable with
        ``(state, netstate)`` for recompile-proof timed windows."""
        return self._synth_jit.lower(
            state, netstate, num_ticks, proposals_per_tick
        )


def reset_durable_rows(
    kernel: ProtocolKernel, state: Pytree, reset: Any,
    boot: Optional[Pytree] = None,
) -> Pytree:
    """Rebuild the ``reset``-masked ``[G, R]`` replica rows from only the
    kernel's declared durable leaves: ``DURABLE_SCALARS`` /
    ``DURABLE_WINDOWS`` entries keep their values verbatim (they are the
    very arrays the host WAL-logs, so the current row IS the last durable
    record), and every other leaf is rewound to its freshly-booted
    ``boot`` value — the same thing a host crash-restart does
    (``init_state`` then ``restore_durable``).  The boot template, NOT
    zeros, matters for safety: volatile leaves like the lease holdoffs
    (``ll_left``/``gset_ttl`` boot FULL so a reborn follower cannot
    immediately vote a challenger in under a live lease), the ``leader``
    belief (boots -1, and 0 is a real replica id), and the per-replica
    PRNG lanes all carry deliberately nonzero boot values.  Leaves
    absent from ``boot`` (the engine-attached telemetry block) zero.
    Pure and jit-safe; every state leaf leads with ``[G, R]`` by
    contract rule C1, so one mask reshape covers all."""
    durable = frozenset(kernel.DURABLE_SCALARS or ()) | frozenset(
        kernel.DURABLE_WINDOWS or ()
    )
    boot = boot or {}

    def rewind(key, leaf):
        if key in durable:
            return leaf
        m = reset.reshape(reset.shape + (1,) * (leaf.ndim - 2))
        fresh = boot.get(key)
        if fresh is None:
            fresh = jnp.zeros_like(leaf)
        return jnp.where(m, fresh, leaf)

    return {k: rewind(k, v) for k, v in state.items()}


def _constrain_carry(mesh, state: Pytree, netstate: Pytree):
    """Pin the scan carry to its (group, replica) mesh layout — applied
    every tick so GSPMD never migrates the carry off its shards (and the
    netmodel's in-group delivery lowers to the replica-axis
    all-to-all)."""
    return (
        shardlib.constrain_state(mesh, state),
        shardlib.constrain_netstate(mesh, netstate),
    )


def _tick(
    kernel: ProtocolKernel,
    net: NetModel,
    boot: Pytree,
    constrain,
    state: Pytree,
    netstate: Pytree,
    inputs: Dict[str, Any],
) -> Tuple[Pytree, Pytree, StepEffects]:
    if constrain is not None:
        state, netstate = constrain(state, netstate)
    ctrl = ControlInputs(
        alive=inputs.get("alive"), link_up=inputs.get("link_up"),
        reset=inputs.get("reset"),
    )
    if ctrl.reset is not None:
        # durable device crash: the replica starts this tick reborn —
        # durable lanes intact, every volatile row rewound to its boot
        # value — and its own step, outbox, and the freeze fallback
        # below all see the post-crash state
        state = reset_durable_rows(kernel, state, ctrl.reset, boot)
    netstate, inbox = net.pop(netstate, ctrl)
    new_state, outbox, fx = kernel.step(state, inbox, inputs)
    if ctrl.alive is not None:
        # freeze paused replicas: every state leaf has leading dims [G, R]
        alive = ctrl.alive

        def freeze(new, old):
            m = alive.reshape(alive.shape + (1,) * (new.ndim - 2))
            return jnp.where(m, new, old)

        new_state = jax.tree.map(freeze, new_state, state)

        # effects must reflect the freeze: bars mirror the frozen state and
        # per-replica event extras are zeroed (a paused replica has no
        # events this tick)
        def mask_extra(leaf):
            if leaf.ndim >= 2 and leaf.shape[:2] == alive.shape:
                m = alive.reshape(alive.shape + (1,) * (leaf.ndim - 2))
                return jnp.where(m, leaf, jnp.zeros_like(leaf))
            return leaf

        fx = StepEffects(
            commit_bar=new_state["commit_bar"],
            exec_bar=new_state["exec_bar"],
            extra={k: mask_extra(v) for k, v in fx.extra.items()},
        )
    if telemetry.TELEM_KEY in new_state:
        # drop/delay lanes are accounted where the masks and jitter draws
        # live; a dead sender's masked messages are pause semantics, not
        # drops (netmodel.push excludes them)
        netstate, tel = net.push(
            netstate, outbox, ctrl, telem=new_state[telemetry.TELEM_KEY]
        )
        new_state = dict(new_state, **{telemetry.TELEM_KEY: tel})
    else:
        netstate = net.push(netstate, outbox, ctrl)
    if constrain is not None:
        new_state, netstate = constrain(new_state, netstate)
    return new_state, netstate, fx


def _run_scan(kernel, net, boot, constrain, state, netstate, inputs_seq,
              collect):
    def body(carry, inp):
        st, ns = carry
        st, ns, fx = _tick(kernel, net, boot, constrain, st, ns, inp)
        return (st, ns), (fx if collect else None)

    (state_f, net_f), fxs = jax.lax.scan(body, (state, netstate), inputs_seq)
    return state_f, net_f, fxs


def _run_synth(kernel, net, boot, constrain, state, netstate, num_ticks,
               proposals_per_tick):
    G = kernel.G

    R = kernel.R

    def body(carry, t):
        st, ns = carry
        inputs = {
            "n_proposals": jnp.full((G,), proposals_per_tick, jnp.int32),
            "value_base": jnp.full((G,), t * proposals_per_tick, jnp.int32),
            # saturating host applier: kernels running with
            # exec_follows_commit=False still make progress
            "exec_floor": jnp.full((G, R), 1 << 30, jnp.int32),
        }
        st, ns, fx = _tick(kernel, net, boot, constrain, st, ns, inputs)
        return (st, ns), None

    (state_f, net_f), _ = jax.lax.scan(
        body, (state, netstate), jnp.arange(num_ticks, dtype=jnp.int32)
    )
    return state_f, net_f
