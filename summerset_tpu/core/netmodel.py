"""Pure-functional lockstep network model.

The TPU-native replacement for the reference's peer-to-peer TCP mesh
(``TransportHub``, ``src/server/transport.rs``) *and* for its netem-based
WAN emulation (``scripts/utils/net.py`` applying ``tc qdisc netem`` delay /
jitter / rate per veth interface — SURVEY.md §4.3): message delivery is a
tensor transform, link delay is a delay-line buffer measured in ticks, and
packet loss / partitions / paused replicas are masks applied to the ``flags``
lane of every message record.

Reliability semantics: the reference treats TCP as an infinitely-retried
reliable FIFO channel (``transport.rs:3-7``).  Here a *delivered* tick-`t`
outbox arrives exactly once at tick ``t + delay``; a *masked* message is
lost forever (the analog of a TCP connection reset mid-flight) — protocols
must tolerate loss via their retry machinery (go-back-N accept streams,
heartbeat-carried state), which the kernels implement.  Per-link FIFO
ordering holds because jitter is drawn per-source-per-tick and bounded, and
all protocol streams carry cumulative frontiers, so reordering within the
jitter window is harmless.

Delivery orientation: outbox per-pair fields are ``[G, R_src, R_dst]``; the
inbox presents them transposed to ``[G, R_dst, R_src]`` so that receiver
code indexes axis 1 = self, axis 2 = sender.  Broadcast window lanes
``[G, R_src, W]`` are delivered unchanged (receiver indexes axis 1 by
sender).  When the replica axis is sharded over the mesh, this transpose
lowers to an all-to-all over ICI (see ``core/sharding.py``).

Quorum-tally lanes (``core/quorum.py``): kernels compiled with
``tally="collective"`` declare their accept-reply / reconstruct-request
lanes in ``TALLY_LANES`` and emit them as per-source ``[G, R]``
broadcast lanes — the pairwise R² fan-out of destination-independent
records skips the pair-shaped delay-line enqueue entirely, and on a
replica-sharded mesh their delivery is ONE all-gather instead of the
all-to-all.  In both modes these lanes' delay-line handling runs under
the ``quorum_tally`` phase scope so graftprof attributes the tally
transport cost.

Per-tick call order (driven by the engine):

1. ``netstate, inbox = net.pop(netstate, ctrl)``   — messages due this tick
2. ``state, outbox, fx = kernel.step(state, inbox, inputs)``
3. ``netstate = net.push(netstate, outbox, ctrl)`` — enqueue + advance tick
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import prng
from .quorum import tally_scope

Pytree = Any

#: resolved value of ``NetConfig.pack_lanes=None`` on the uniform-1-tick
#: path.  Landed "default off until measured" (PERF.md round 6);
#: graftprof measured the A/B on the bench shape (PERF.md round 11:
#: fewer delay-line HLO ops, steady tick within noise of the loose
#: path), so the default bench/serving path now packs.  Deeper delay
#: lines (jitter) always stay loose — the jittered enqueue is
#: per-lane-shaped.
PACK_LANES_DEFAULT_D1 = True


@dataclasses.dataclass(frozen=True)
class NetConfig:
    """Static network emulation parameters (all in ticks / probabilities)."""

    delay_ticks: int = 1      # uniform base one-way delay (>= 1)
    max_delay_ticks: int = 1  # delay-line depth D (auto-raised to fit jitter)
    jitter_ticks: int = 0     # per-(source, tick) extra delay in [0, jitter]
    drop_rate: float = 0.0    # iid per-message loss probability
    # pack same-shape int32 lanes into single stacked tensors through the
    # delay line: one big buffer write/read/transpose instead of ~17
    # per-lane ops per tick — the per-op dispatch floor identified in
    # PERF.md.  Semantically identical (equivalence-tested).  None =
    # the measured default: PACK_LANES_DEFAULT_D1 when the delay line
    # is the uniform 1-tick swap, off for deeper delay lines.  The
    # sentinel is KEPT in the field (resolution lives in
    # ``lanes_packed``) so ``dataclasses.replace`` on a default config
    # re-derives against the new delay depth instead of carrying a
    # stale resolved True into a jittered variant and raising.
    pack_lanes: Optional[bool] = None

    def __post_init__(self):
        if self.delay_ticks < 1:
            raise ValueError("delay_ticks must be >= 1")
        if self.max_delay_ticks < self.delay_ticks + self.jitter_ticks:
            object.__setattr__(
                self, "max_delay_ticks", self.delay_ticks + self.jitter_ticks
            )
        if self.pack_lanes and self.max_delay_ticks != 1:
            # packing targets the uniform-1-tick bench path; the jittered
            # delay-line enqueue is per-lane-shaped (only an EXPLICIT
            # True conflicts — the None default resolves to off here)
            raise ValueError("pack_lanes requires max_delay_ticks == 1")

    @property
    def lanes_packed(self) -> bool:
        """The resolved packing decision (what NetModel consults)."""
        if self.pack_lanes is None:
            return PACK_LANES_DEFAULT_D1 and self.max_delay_ticks == 1
        return bool(self.pack_lanes)


@dataclasses.dataclass
class ControlInputs:
    """Per-tick fault-injection masks (the manager-oracle pause/partition
    analog; reference ``clusman.rs`` pause/resume and tc-netem partitions).

    ``alive``:   [G, R] bool — False freezes a replica (pause): it sends
                 nothing, receives nothing, and its state does not advance.
    ``link_up``: [G, R, R] bool — False drops messages src->dst (partition).
    ``reset``:   [G, R] bool — True rebuilds the replica's state row from
                 only its kernel's declared durable leaves at the START of
                 the tick: every volatile leaf is rewound to its
                 freshly-booted ``init_state`` value, so a device crash
                 loses volatile state exactly like a host crash-restart
                 does (``engine.reset_durable_rows`` — the vectorized
                 in-kernel form of the host's boot-then-
                 ``restore_durable`` contract).  Freeze-and-thaw
                 (``alive`` alone) remains the pause analog; ``reset`` is
                 the durable crash analog.

    The partition constructors below build the standard nemesis shapes so
    tests and the fault-schedule compiler (host/nemesis.py) never
    hand-assemble ``[G, R, R]`` index masks.  All return ``[G, R, R]``
    bool arrays (self-links stay up) and compose with ``&``.
    """

    alive: Any = None
    link_up: Any = None
    reset: Any = None

    @staticmethod
    def all_up(G: int, R: int) -> "ControlInputs":
        return ControlInputs(
            alive=jnp.ones((G, R), jnp.bool_),
            link_up=jnp.ones((G, R, R), jnp.bool_),
        )

    @staticmethod
    def links_all_up(G: int, R: int):
        """[G, R, R] mask with every link up."""
        return jnp.ones((G, R, R), jnp.bool_)

    @staticmethod
    def split_links(G: int, R: int, side):
        """Symmetric partition: every link between ``side`` and its
        complement is down in BOTH directions; links within each side
        stay up (the classic majority/minority split)."""
        a = np.zeros(R, bool)
        a[list(side)] = True
        link = np.ones((G, R, R), bool)
        cross = a[:, None] ^ a[None, :]          # [R, R] across the cut
        link &= ~cross[None, :, :]
        return jnp.asarray(link)

    @staticmethod
    def isolate_links(G: int, R: int, *victims):
        """Isolate each victim from every other replica (both
        directions); victims keep only their self-link.  With one victim
        this is the 'isolate-one' nemesis; with several, each victim is
        alone (victims cannot talk to each other either)."""
        v = np.zeros(R, bool)
        v[list(victims)] = True
        link = np.ones((G, R, R), bool)
        touched = v[:, None] | v[None, :]        # any link touching a victim
        link &= ~touched[None, :, :]
        link |= np.eye(R, dtype=bool)[None, :, :]
        return jnp.asarray(link)

    @staticmethod
    def one_way_down(G: int, R: int, src: int, dst: int):
        """Asymmetric link fault: messages ``src -> dst`` are dropped;
        the reverse direction still delivers."""
        link = np.ones((G, R, R), bool)
        link[:, src, dst] = False
        return jnp.asarray(link)

    @staticmethod
    def skew_alive(G: int, R: int, ticks: int, rates: dict,
                   offset: int = 0):
        """Per-replica clock-skew as duty-cycled ``alive`` masks:
        ``[T, G, R]`` where replica ``r`` with rate ``rates[r]`` in
        (0, 1] steps only on ticks where ``floor((t+1)*rate)`` advances —
        i.e. its tick counter runs at ``rate`` times the cluster's.
        Deterministic (no RNG) so a fault schedule containing skew stays
        byte-identical per seed.  This is the adversarial superset of
        real clock skew under lockstep semantics: a skipped tick freezes
        the replica's countdowns (its lease/election clocks run slow)
        AND loses that tick's deliveries, like a late process scheduled
        around its socket reads.  ``offset`` phases the duty cycle (used
        by the fault compiler to start a skew window mid-schedule)."""
        alive = np.ones((ticks, G, R), bool)
        t = np.arange(offset, offset + ticks, dtype=np.float64)
        for r, rate in rates.items():
            if not 0.0 < rate <= 1.0:
                raise ValueError(f"skew rate must be in (0, 1]: {rate}")
            on = np.floor((t + 1) * rate) > np.floor(t * rate)
            alive[:, :, int(r)] &= on[:, None]
        return jnp.asarray(alive)


class NetModel:
    """Delay-line message delivery with loss/partition masking.

    Netstate: ``bufs`` — per-field arrays of shape ``[D, ...field...]`` where
    slot ``(cursor + d) % D`` holds messages due ``d`` ticks from now; a
    ``cursor``; and a PRNG lane.  With the default ``D == 1`` (uniform 1-tick
    delay, no jitter) pop/push degenerate to a buffer swap + transpose that
    XLA fuses into the step kernel.
    """

    def __init__(self, cfg: NetConfig, num_groups: int, population: int,
                 broadcast_lanes: frozenset,
                 tally_lanes: frozenset = frozenset()):
        self.cfg = cfg
        self.G = num_groups
        self.R = population
        self.broadcast_lanes = broadcast_lanes
        # quorum-tally lanes (core/quorum.py): their delay-line handling
        # runs under the ``quorum_tally`` phase scope in BOTH tally
        # modes, so graftprof attributes the tally transport cost
        # head-to-head (pairwise [G, R, R] lanes vs the collective
        # [G, R] per-source records).  They stay out of the packed
        # stacks for the same reason: attribution needs them loose.
        self.tally_lanes = tally_lanes
        # lane-packing plan: filled lazily from the outbox structure
        self._pack_pair: tuple = ()
        self._pack_bcast: tuple = ()

    def _lane_scope(self, key: str):
        """Tally lanes trace under the quorum_tally phase scope; every
        other lane's transport stays unattributed scan plumbing."""
        if key in self.tally_lanes:
            return tally_scope()
        return contextlib.nullcontext()

    def _plan_packing(self, zero_outbox: Pytree) -> None:
        """Group same-shape int32 lanes for stacked transport: per-pair
        [G, R, R] lanes and per-window broadcast [G, R_src, W] lanes
        (uniform W only).  ``flags`` (uint32, masked), odd shapes, and
        the quorum-tally lanes (kept loose for phase attribution) stay
        unpacked.  Broadcast lanes are grouped by their FULL shape, so
        a [G, R] per-source lane (the collective tally records) can
        never poison the [G, R, W] window-lane stack."""
        pair, bcast = [], []
        bshape = None
        for k, v in zero_outbox.items():
            if (
                k == "flags"
                or v.dtype != jnp.int32
                or k in self.tally_lanes
            ):
                continue
            if k in self.broadcast_lanes:
                if v.ndim != 3:
                    continue  # only [G, R_src, W] window lanes stack
                if bshape is None:
                    bshape = v.shape
                if v.shape == bshape:
                    bcast.append(k)
            elif v.shape == (self.G, self.R, self.R):
                pair.append(k)
        self._pack_pair = tuple(sorted(pair))
        self._pack_bcast = tuple(sorted(bcast))

    def _pack(self, outbox: Pytree) -> Pytree:
        packed = {
            k: v for k, v in outbox.items()
            if k not in self._pack_pair and k not in self._pack_bcast
        }
        if self._pack_pair:
            packed["__pair__"] = jnp.stack(
                [outbox[k] for k in self._pack_pair]
            )
        if self._pack_bcast:
            packed["__bcast__"] = jnp.stack(
                [outbox[k] for k in self._pack_bcast]
            )
        return packed

    def _unpack(self, packed: Pytree) -> Pytree:
        out = {
            k: v for k, v in packed.items()
            if k not in ("__pair__", "__bcast__")
        }
        if "__pair__" in packed:
            for i, k in enumerate(self._pack_pair):
                out[k] = packed["__pair__"][i]
        if "__bcast__" in packed:
            for i, k in enumerate(self._pack_bcast):
                out[k] = packed["__bcast__"][i]
        return out

    def init_netstate(self, zero_outbox: Pytree, seed: int = 17) -> Pytree:
        D = self.cfg.max_delay_ticks
        if self.cfg.lanes_packed:
            self._plan_packing(zero_outbox)
            zero_outbox = self._pack(dict(zero_outbox))
        bufs = jax.tree.map(
            lambda x: jnp.zeros((D,) + x.shape, x.dtype), zero_outbox
        )
        return {
            "bufs": bufs,
            "cursor": jnp.int32(0),
            # absolute tick of the last enqueued delivery per source; keeps
            # jittered due-slots strictly increasing (FIFO, no clobbering)
            "last_due": jnp.zeros((self.G, self.R), jnp.int32),
            "tick": jnp.int32(0),
            "rng": prng.seed_state(17 + seed, (self.G, self.R, self.R)),
        }

    def pop(
        self, netstate: Pytree, ctrl: Optional[ControlInputs] = None
    ) -> Tuple[Pytree, Pytree]:
        """Dequeue the messages due this tick, oriented for receivers."""
        D = self.cfg.max_delay_ticks
        cursor = netstate["cursor"]
        bufs = netstate["bufs"]
        raw = {}
        if D == 1:
            for k, b in bufs.items():
                with self._lane_scope(k):
                    raw[k] = b[0]
        else:
            nbufs = {}
            for k, b in bufs.items():
                with self._lane_scope(k):
                    raw[k] = b[cursor]
                    nbufs[k] = b.at[cursor].set(jnp.zeros_like(b[0]))
            bufs = nbufs

        # receiver-side mask: a replica paused *now* receives nothing
        flags = raw["flags"]
        if ctrl is not None and ctrl.alive is not None:
            flags = jnp.where(ctrl.alive[:, None, :], flags, jnp.uint32(0))
        raw = dict(raw, flags=flags)

        if self.cfg.lanes_packed:
            # ONE transpose over the stacked pair tensor, then cheap
            # per-lane slices back into the dict the kernels consume
            inbox = {}
            for k, v in raw.items():
                with self._lane_scope(k):
                    if k == "__pair__":
                        v = jnp.swapaxes(v, 2, 3)
                    elif k != "__bcast__" and k not in self.broadcast_lanes:
                        v = jnp.swapaxes(v, 1, 2)
                    inbox[k] = v
            inbox = self._unpack(inbox)
        else:
            inbox = {}
            for k, v in raw.items():
                with self._lane_scope(k):
                    inbox[k] = (
                        v if k in self.broadcast_lanes
                        else jnp.swapaxes(v, 1, 2)
                    )
        return dict(netstate, bufs=bufs), inbox

    def push(
        self,
        netstate: Pytree,
        outbox: Pytree,
        ctrl: Optional[ControlInputs] = None,
        telem: Optional[Any] = None,
    ) -> Pytree:
        """Enqueue this tick's outbox with sender-side masking; advance tick.

        With ``telem`` (the ``[G, R, K]`` metric-lane block from
        ``core/telemetry.py``) the drop/delay lanes are accounted here —
        where the loss masks and jitter draws actually live — and the
        updated block is returned alongside: ``(netstate, telem)``.
        """
        from . import telemetry as _tm

        cfg = self.cfg
        D = cfg.max_delay_ticks
        bufs = netstate["bufs"]
        cursor = netstate["cursor"]
        rng = netstate["rng"]

        flags = outbox["flags"]
        mask = jnp.ones(flags.shape, jnp.bool_)
        alive_src = None
        masked_any = cfg.drop_rate > 0.0
        if ctrl is not None and ctrl.alive is not None:
            alive_src = ctrl.alive[:, :, None]
            mask &= alive_src  # dead source sends nothing
            masked_any = True
        if ctrl is not None and ctrl.link_up is not None:
            mask &= ctrl.link_up
            masked_any = True
        if cfg.drop_rate > 0.0:
            rng, u = prng.uniform_unit(rng)
            mask &= u >= cfg.drop_rate
        if telem is not None and masked_any:
            # a message a live sender emitted but the mask ate is a drop;
            # a dead sender emitting nothing is pause semantics, and its
            # lane row must stay frozen.  Skipped entirely (static
            # condition) when no masks exist this compilation — the
            # steady bench path pays nothing for the lane.
            lost = (flags != 0) & ~mask
            if alive_src is not None:
                lost &= alive_src
            telem = _tm.bump(
                telem, "net_drops", jnp.sum(lost.astype(jnp.int32), axis=2)
            )
        outbox = dict(outbox, flags=jnp.where(mask, flags, jnp.uint32(0)))
        if self.cfg.lanes_packed:
            outbox = self._pack(outbox)

        tick = netstate["tick"]
        last_due = netstate["last_due"]
        if D == 1:
            nbufs = {}
            for k, b in bufs.items():
                with self._lane_scope(k):
                    nbufs[k] = b.at[0].set(outbox[k])
            bufs = nbufs
        else:
            # Jitter per (group, source) — not per link — so a source's
            # broadcast window lanes land in the same delay slot as its
            # per-pair records and receivers never see torn messages.
            delay = jnp.full((self.G, self.R), cfg.delay_ticks, jnp.int32)
            if cfg.jitter_ticks > 0:
                rng_src = rng[:, :, 0]
                rng_nxt, extra = prng.uniform_int(
                    rng_src, 0, cfg.jitter_ticks + 1
                )
                rng = rng.at[:, :, 0].set(rng_nxt)
                delay = delay + extra
                if telem is not None:
                    # total jitter ticks added to ENQUEUED sends: the
                    # per-source draw happens every tick, but only
                    # messages actually on the wire carry the delay (an
                    # idle source must not inflate the lane)
                    nsent = jnp.sum(
                        (outbox["flags"] != 0).astype(jnp.int32), axis=2
                    )
                    telem = _tm.bump(
                        telem, "net_delay_ticks", extra * nsent
                    )
            # Clamp the absolute due tick to be strictly after the source's
            # previous one (FIFO + no slot collisions: an in-flight message
            # is never clobbered by a later send) and within the ring.
            due_abs = jnp.clip(
                jnp.maximum(tick + delay, last_due + 1), tick + 1, tick + D
            )
            last_due = due_abs
            due = (cursor + (due_abs - tick)) % D  # [G, R_src]
            arange_d = jnp.arange(D, dtype=jnp.int32)

            def enqueue(buf, field):
                # buf: [D, G, R_src, ...]; one-hot over D on the source's due
                # slot, broadcast over trailing dims (dst and/or window).
                oh = arange_d[:, None, None] == due[None]  # [D, G, R_src]
                oh = oh.reshape(oh.shape + (1,) * (field.ndim - 2))
                return jnp.where(oh, field[None], buf)

            nbufs = {}
            for k in outbox:
                with self._lane_scope(k):
                    nbufs[k] = enqueue(bufs[k], outbox[k])
            bufs = nbufs

        out = {
            "bufs": bufs,
            "cursor": (cursor + 1) % jnp.int32(max(D, 1)),
            "last_due": last_due,
            "tick": tick + 1,
            "rng": rng,
        }
        return out if telem is None else (out, telem)
