"""Protocol SPI for vectorized lockstep consensus kernels.

This is the TPU-native analog of the reference's ``GenericReplica`` trait
(``src/server/replica.rs:16-42``): where the reference dispatches one
``tokio::select!`` event loop per replica process, a :class:`ProtocolKernel`
defines pure functions over batched state — ``init_state`` builds the
struct-of-arrays pytree for ``[num_groups, population]`` replicas, and
``step`` advances every replica of every group by one lockstep tick.

Design rules (required for masking / sharding to work uniformly):

- every state leaf has leading dims ``[G, R]`` (group, replica), and the
  state dict must contain int32 ``commit_bar``/``exec_bar`` leaves (the
  engine mirrors them into effects when masking paused replicas);
- every outbox leaf is either a per-directed-pair field ``[G, R_src, R_dst]``
  (delivered transposed to ``[G, R_dst, R_src]``) or a broadcast window lane
  ``[G, R_src, W]`` named in ``broadcast_lanes`` (delivered as-is; receivers
  index axis 1 by sender);
- the outbox must contain a uint32 ``flags`` per-pair field; the network
  model zeroes ``flags`` on dead/partitioned/dropped links and consumers
  must gate every read on it;
- no data-dependent Python control flow: everything is masked updates.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, FrozenSet, Tuple

import jax

from . import telemetry

Pytree = Any


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StepEffects:
    """Per-tick observables extracted by the engine.

    ``commit_bar``/``exec_bar``: ``[G, R]`` int32 snapshots after the tick.
    ``extra``: protocol-specific dict of arrays (e.g. read results, lease
    status) — must be fixed-shape.
    """

    commit_bar: Any
    exec_bar: Any
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


class ProtocolKernel:
    """Base class for vectorized protocol kernels.

    Subclasses are constructed with static geometry (``num_groups``,
    ``population``, ``window``) plus a protocol config dataclass, and are
    hashable/static from JAX's perspective — all dynamic data lives in the
    state pytree.
    """

    name: str = "generic"
    # outbox leaves that are [G, R_src, W] broadcast lanes (not per-pair)
    broadcast_lanes: FrozenSet[str] = frozenset()

    # -- durable acceptor contract ------------------------------------------
    # State arrays forming this kernel's per-replica durable acceptor
    # record: the host WAL-logs row [g, me] of each named array before the
    # acks referencing it leave the process, and feeds the last logged
    # record per group back through ``restore_durable`` on crash-restart.
    # ``None`` (the default) means the kernel declares NO durable contract
    # — the host REFUSES to serve it rather than silently running without
    # durability (the reference persists acceptor state for every served
    # protocol: multipaxos durability.rs:85-216, raft/mod.rs:144-176).
    DURABLE_SCALARS = None   # tuple[str] of [G, R] arrays
    DURABLE_WINDOWS = None   # tuple[str] of [G, R, W] arrays
    VALUE_WINDOW = "win_val"  # the window lane holding payload value ids

    def restore_durable(self, st, g: int, me: int, rec: dict, floor: int):
        """Reinstate acceptor row ``(g, me)`` from the last logged durable
        record ``rec`` ({field: int | list}), given the host applier's
        recovered exec floor.  Mutates ``st`` in place.

        Default: every DURABLE_SCALARS entry is restored as
        ``max(rec, floor)``, the dur/commit/exec bars are raised to the
        floor, and DURABLE_WINDOWS content is copied verbatim — correct
        for kernels whose scalars are all monotone frontiers (the basic
        protocols).  Kernels with paired or non-frontier durable state
        (ballot/vote pairs, term/voted_for, conf slots) override this."""
        if self.DURABLE_SCALARS is None:
            raise NotImplementedError(
                f"{type(self).__name__} declares no durable contract"
            )
        import jax.numpy as jnp

        i32 = jnp.int32
        fl = i32(floor)
        for k in self.DURABLE_SCALARS:
            st[k] = st[k].at[g, me].set(jnp.maximum(i32(rec[k]), fl))
        for k in ("dur_bar", "commit_bar", "exec_bar"):
            if k in st and k not in self.DURABLE_SCALARS:
                st[k] = st[k].at[g, me].max(fl)
        for k in self.DURABLE_WINDOWS:
            st[k] = st[k].at[g, me].set(jnp.asarray(rec[k], st[k].dtype))

    def __init__(self, num_groups: int, population: int, window: int):
        if population < 1 or population > 32:
            raise ValueError("population must be in [1, 32] (uint32 bitmap lanes)")
        self.num_groups = num_groups
        self.population = population
        self.window = window

    # -- geometry shorthands -------------------------------------------------
    @property
    def G(self) -> int:
        return self.num_groups

    @property
    def R(self) -> int:
        return self.population

    @property
    def W(self) -> int:
        return self.window

    @property
    def quorum(self) -> int:
        return self.population // 2 + 1

    # -- telemetry SPI -------------------------------------------------------
    # The engine attaches a [G, R, K] int32 metric-lane block to the state
    # (core/telemetry.py); each step folds per-tick contributions into it.
    # Presence of the block is a static condition: states without it (the
    # profile_tick ablation, hand-built test states) compile a lane-free
    # variant at zero cost.

    def _telemetry(self, old: Pytree, s: Pytree, c: Any) -> Dict[str, Any]:
        """Hook: lane name -> [G, R] per-tick increments (bool or int32).

        ``old`` is the pre-step state, ``s`` the post-phase state dict,
        ``c`` the step's scratch namespace.  The base implementation
        derives the protocol-generic lanes every kernel has by contract
        (commit_bar) or by common window shape (win_bal); subclasses
        extend the dict with their protocol-specific lanes.
        """
        import jax.numpy as jnp

        tel = {
            "commits": jnp.maximum(s["commit_bar"] - old["commit_bar"], 0),
        }
        n_new = getattr(c, "n_new", None)
        if n_new is not None:
            tel["proposals"] = n_new
        for key in ("next_slot", "prop_bar"):  # common frontier names
            if key in s:
                tel["win_occupancy_hw"] = self._occupancy_span(s, key)
                break
        return tel

    def _occupancy_span(self, s, hi_key: str):
        """Cheap window-occupancy proxy for the high-water lane: the live
        span ``frontier - exec_bar`` clipped to [0, W] — the number of
        slots the ring must keep live, i.e. the window-stall pressure.
        An exact ``count(win_* > 0)`` reduce over [G, R, W] costs ~7% of
        a steady G=4096 CPU tick on its own (ablation-measured), which
        would bust the 5% telemetry budget by itself; the span is O(G,R)
        and is the quantity the propose/append window guards actually
        gate on."""
        import jax.numpy as jnp

        span = s[hi_key] - s["exec_bar"]
        if "vote_bar" in s and hi_key != "vote_bar":
            span = jnp.maximum(span, s["vote_bar"] - s["exec_bar"])
        return jnp.clip(span, 0, self.window)

    def _accumulate_telemetry(self, old: Pytree, s: Pytree, c: Any) -> None:
        """Fold this tick's lane contributions into ``s['telem']`` (no-op
        when the state carries no lane block)."""
        if telemetry.TELEM_KEY in s:
            s[telemetry.TELEM_KEY] = telemetry.accumulate(
                s[telemetry.TELEM_KEY], self._telemetry(old, s, c)
            )

    # -- SPI -----------------------------------------------------------------
    def init_state(self, seed: int = 0) -> Pytree:
        raise NotImplementedError

    def zero_outbox(self) -> Pytree:
        """An all-invalid outbox (flags == 0); defines the outbox structure."""
        raise NotImplementedError

    def step(
        self, state: Pytree, inbox: Pytree, inputs: Dict[str, Any]
    ) -> Tuple[Pytree, Pytree, StepEffects]:
        """Advance one lockstep tick.

        ``inbox`` has the same structure as ``zero_outbox`` but with per-pair
        fields transposed to ``[G, R_dst, R_src]``.  ``inputs`` carries host
        inputs for this tick (client proposals, exec floor, ...).
        """
        raise NotImplementedError

    # JAX static-argument support: kernels are static per (class, geometry,
    # config) so jitted steps cache correctly.  Subclasses store their config
    # dataclass as ``self.config`` so it participates in the cache key.
    def _static_key(self) -> tuple:
        cfg = getattr(self, "config", None)
        cfg_key = dataclasses.astuple(cfg) if dataclasses.is_dataclass(cfg) else cfg
        return (type(self), self.num_groups, self.population, self.window, cfg_key)

    def __hash__(self) -> int:
        return hash(self._static_key())

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ProtocolKernel)
            and self._static_key() == other._static_key()
        )
