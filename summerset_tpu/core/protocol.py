"""Protocol SPI for vectorized lockstep consensus kernels.

This is the TPU-native analog of the reference's ``GenericReplica`` trait
(``src/server/replica.rs:16-42``): where the reference dispatches one
``tokio::select!`` event loop per replica process, a :class:`ProtocolKernel`
defines pure functions over batched state — ``init_state`` builds the
struct-of-arrays pytree for ``[num_groups, population]`` replicas, and
``step`` advances every replica of every group by one lockstep tick.

The design rules that make masking / sharding / durability / telemetry
work uniformly are no longer prose: :data:`KERNEL_CONTRACT` below is the
machine-readable rule table, enforced per registered kernel by the
``summerset_tpu/analysis`` verifier (``scripts/graftlint.py``, CI tier
2e, committed baseline ``LINT.json``).  README "Kernel contract" renders
the same table for humans.
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Dict, FrozenSet, Tuple

import jax

from . import telemetry

Pytree = Any

# --------------------------------------------------------------- phases --
#: name-stack prefix every phase scope carries.  Distinctive on purpose:
#: the graftprof profiler (host/profiling.py) recovers per-phase HLO op
#: counts and measured device time by matching this prefix in compiled
#: HLO ``op_name`` metadata, so it must never collide with a jax- or
#: user-minted scope name.
PHASE_SCOPE_PREFIX = "graftphase__"

#: global phase-scope switch (profiling ablation A/B).  ``named_scope``
#: is trace-time metadata only — flipping this and re-tracing compiles
#: the scope-free variant, which is exactly the ablation the <5%
#: instrumentation-overhead gate (scripts/perf_gate.py) compares
#: against.  Consulted at trace time, so a fresh Engine (new jit
#: wrappers) picks the current setting up.
_PHASE_SCOPES_ENABLED = True


def set_phase_scopes(enabled: bool) -> None:
    """Enable/disable ``jax.named_scope`` phase annotation globally
    (the graftprof instrumentation ablation; default on)."""
    global _PHASE_SCOPES_ENABLED
    _PHASE_SCOPES_ENABLED = bool(enabled)


def phase_scopes_enabled() -> bool:
    return _PHASE_SCOPES_ENABLED


def phase_scope(name: str):
    """The named scope a declared phase runs under (or a no-op context
    when phase scopes are ablated away)."""
    if _PHASE_SCOPES_ENABLED:
        return jax.named_scope(PHASE_SCOPE_PREFIX + name)
    return contextlib.nullcontext()

#: The kernel SPI contract, numbered and linter-enforced.  Every rule is
#: stated against what the runtime actually relies on: the engine's
#: freeze masks reshape on leading ``[G, R]`` (C1), the netmodel
#: transposes axes 1/2 of every non-broadcast outbox leaf and zeroes
#: only ``flags`` on dead links (C3, T1), the host WAL logs the declared
#: durable rows (C5), ``lax.scan`` re-feeds the state structure as its
#: carry (C7), and the model-check / nemesis replay planes assume the
#: step is a pure deterministic function (C6, C8).
KERNEL_CONTRACT: Tuple[Tuple[str, str, str], ...] = (
    ("C1", "state-geometry",
     "every state leaf leads with [G, R]; int32 commit_bar / exec_bar "
     "[G, R] leaves are present (engine freeze masks + effects mirror)"),
    ("C10", "input-declarations",
     "every step-input name the kernel reads — including optional "
     ".get()-style reads, which a trace cannot KeyError on — is a base "
     "input (n_proposals/value_base/exec_floor) or declared in "
     "EXTRA_INPUTS, so the verified/tainted surface covers every lane "
     "the kernel can consume"),
    ("C2", "state-dtype",
     "protocol state is integer/bool only — no float leaves"),
    ("C3", "outbox-shape",
     "the outbox carries a uint32 [G, R, R] 'flags' pair-field; every "
     "other leaf is per-pair [G, R_src, R_dst, ...] (delivered "
     "transposed) or declared in broadcast_lanes and leads with "
     "[G, R_src] (delivered as-is)"),
    ("C4", "outbox-dtype",
     "outbox lanes are integer/bool only"),
    ("C5", "durable-contract",
     "DURABLE_SCALARS / DURABLE_WINDOWS are declared and resolve to "
     "state arrays of the declared shapes ([G, R] scalars, [G, R, ...] "
     "windows); VALUE_WINDOW names one of DURABLE_WINDOWS"),
    ("C6", "step-purity",
     "step traces to a jaxpr with no host callbacks, no effects, and "
     "no nondeterministic primitives (init_state runs eagerly on the "
     "host exactly once and is exempt); explicit mesh collectives "
     "(psum / all_gather / reduce_scatter family) are permitted ONLY "
     "inside the quorum_tally phase scope — the one place the "
     "in-mesh tally plane (core/quorum.py) sanctions cross-replica "
     "aggregation"),
    ("C7", "carry-stability",
     "step returns a state pytree structurally identical (keys, shapes, "
     "dtypes) to its input — the lax.scan carry contract"),
    ("C8", "int-discipline",
     "no floating-point intermediate appears in the step jaxpr (no "
     "silent float32 upcasts in protocol lanes)"),
    ("C9", "telemetry-path",
     "the telem lane block is written only via the stacked "
     "accumulate/bump path in core/telemetry.py, contributed through "
     "the _telemetry hook"),
    ("R2", "range-claims",
     "every RANGE_CLAIMS entry (leaf, lo, hi) is an inductive value-"
     "range invariant: it holds at init_state and is preserved by one "
     "abstract step under the saturating interval semantics of "
     "analysis/ranges.py"),
    ("T1", "flags-gating",
     "every inbox read that lands in a state update, an effects "
     "output, or an outbox lane (a relay hop back onto the wire) "
     "passes a gate (select / mask-multiply) derived — directly or "
     "transitively — from the netmodel-zeroed flags field; "
     "intentional exceptions are declared in TAINT_ALLOW with a reason"),
    ("T9", "suppression-hygiene",
     "every TAINT_ALLOW entry names a flow that still occurs — a stale "
     "suppression is itself a finding, so the allowlist cannot rot"),
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StepEffects:
    """Per-tick observables extracted by the engine.

    ``commit_bar``/``exec_bar``: ``[G, R]`` int32 snapshots after the tick.
    ``extra``: protocol-specific dict of arrays (e.g. read results, lease
    status) — must be fixed-shape.
    """

    commit_bar: Any
    exec_bar: Any
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


class ProtocolKernel:
    """Base class for vectorized protocol kernels.

    Subclasses are constructed with static geometry (``num_groups``,
    ``population``, ``window``) plus a protocol config dataclass, and are
    hashable/static from JAX's perspective — all dynamic data lives in the
    state pytree.
    """

    name: str = "generic"
    # outbox leaves delivered as-is, [G, R_src, ...] (not per-pair)
    broadcast_lanes: FrozenSet[str] = frozenset()

    # -- machine-readable contract metadata (analysis / graftlint) ----------
    # step() inputs this kernel consumes beyond the base lanes every
    # kernel gets (n_proposals [G], value_base [G], exec_floor [G, R]),
    # as (name, shape_code): g=[G], gr=[G, R], grr=[G, R, R],
    # gp=[G, P] proposal-width lists.  The verifier traces step against
    # exactly this superset: an undeclared input either KeyErrors the
    # trace (direct subscript reads) or — for optional `.get()`-style
    # reads — silently drops that branch from the verified/tainted
    # surface.  The declaration is no longer honor-system: rule C10
    # AST-cross-checks every input-name literal the kernel's class
    # bodies read against this table.
    EXTRA_INPUTS: Tuple[Tuple[str, str], ...] = ()
    # -- quorum-tally plane (core/quorum.py) --------------------------------
    # Outbox lanes that carry per-source tally records (accept-reply
    # frontiers, reconstruct-request ranges): values that do not depend
    # on the destination, fanned out pairwise only because the lane
    # shape demanded it.  Under ``config.tally == "collective"`` these
    # lanes shrink from ``[G, R_src, R_dst]`` pair fields to per-source
    # ``[G, R_src]`` broadcast lanes (delivery = the broadcast path; an
    # all-gather over a sharded replica axis) while the ``flags``
    # pair-field keeps per-link masking/visibility semantics — see the
    # core/quorum.py module doc for the equivalence argument.  The
    # netmodel tags these lanes' delay-line transport with the
    # ``quorum_tally`` phase scope in BOTH modes so graftprof compares
    # the tally cost head-to-head.
    TALLY_LANES: Tuple[str, ...] = ()
    # -- phase registry (graftprof) -----------------------------------------
    # The kernel's named step phases, in execution order, as
    # (phase_name, method_name) pairs.  Each method has the uniform
    # mutate-in-place signature ``meth(self, s, c)`` (``s`` = the state
    # dict under construction, ``c`` = the step's scratch namespace) and
    # is invoked by :meth:`_run_phases` under
    # ``jax.named_scope(PHASE_SCOPE_PREFIX + phase_name)``.  The scopes
    # ride the jaxpr name stack into compiled-HLO ``op_name`` metadata,
    # which is what lets host/profiling.py attribute analytic op counts
    # AND measured device time to phases — the PERF.md breakdown table
    # is generated from these declarations, not hand-maintained.
    # Subclasses inherit the family's table (overriding a phase METHOD
    # keeps its attribution); kernels with extra top-level work extend
    # the tuple.  ``scripts/perf_gate.py`` gates the declared-name set
    # against the committed PROFILE.json, and tests/test_profiling.py
    # asserts every registered kernel declares >= 1 phase whose scopes
    # actually appear in the traced jaxpr.
    PHASES: Tuple[Tuple[str, str], ...] = ()
    # declared-intentional ungated inbox->state flows for the
    # flags-taint pass, as (inbox_leaf, state_leaf, reason).  The pass
    # fails on any flow not listed here AND on stale entries that no
    # longer occur — suppressions are explicit and cannot rot.
    TAINT_ALLOW: Tuple[Tuple[str, str, str], ...] = ()
    # -- value-range proof plane (analysis/ranges.py) -----------------------
    # Author-asserted per-leaf bounds as (state_leaf, lo, hi), inclusive.
    # The range pass derives inductive interval invariants for every
    # state leaf mechanically; entries here are *additional* claims a
    # kernel wants pinned tighter than the derived invariant (e.g. a
    # window index provably < W).  Each is checked inductive — holds at
    # init_state AND is preserved by one abstract step — and a violated
    # claim is an R2 finding.  The derived invariants themselves need no
    # declaration: they are serialized into LINT.json and cross-checked
    # against every state the exhaustive model checker visits.
    RANGE_CLAIMS: Tuple[Tuple[str, int, int], ...] = ()

    # -- durable acceptor contract ------------------------------------------
    # State arrays forming this kernel's per-replica durable acceptor
    # record: the host WAL-logs row [g, me] of each named array before the
    # acks referencing it leave the process, and feeds the last logged
    # record per group back through ``restore_durable`` on crash-restart.
    # ``None`` (the default) means the kernel declares NO durable contract
    # — the host REFUSES to serve it rather than silently running without
    # durability (the reference persists acceptor state for every served
    # protocol: multipaxos durability.rs:85-216, raft/mod.rs:144-176).
    # The SAME declarations drive the device plane's durable crash model:
    # ``engine.reset_durable_rows`` keeps exactly these leaves and
    # rewinds every volatile one to its freshly-booted init_state value
    # when a ``device_reset`` nemesis mask fires — a kernel whose safety
    # state is fully declared here survives both host and device
    # crash-restarts by construction.
    DURABLE_SCALARS = None   # tuple[str] of [G, R] arrays
    DURABLE_WINDOWS = None   # tuple[str] of [G, R, W] arrays
    VALUE_WINDOW = "win_val"  # the window lane holding payload value ids

    def restore_durable(self, st, g: int, me: int, rec: dict, floor: int):
        """Reinstate acceptor row ``(g, me)`` from the last logged durable
        record ``rec`` ({field: int | list}), given the host applier's
        recovered exec floor.  Mutates ``st`` in place.

        Default: every DURABLE_SCALARS entry is restored as
        ``max(rec, floor)``, the dur/commit/exec bars are raised to the
        floor, and DURABLE_WINDOWS content is copied verbatim — correct
        for kernels whose scalars are all monotone frontiers (the basic
        protocols).  Kernels with paired or non-frontier durable state
        (ballot/vote pairs, term/voted_for, conf slots) override this."""
        if self.DURABLE_SCALARS is None:
            raise NotImplementedError(
                f"{type(self).__name__} declares no durable contract"
            )
        import jax.numpy as jnp

        i32 = jnp.int32
        fl = i32(floor)
        for k in self.DURABLE_SCALARS:
            st[k] = st[k].at[g, me].set(jnp.maximum(i32(rec[k]), fl))
        for k in ("dur_bar", "commit_bar", "exec_bar"):
            if k in st and k not in self.DURABLE_SCALARS:
                st[k] = st[k].at[g, me].max(fl)
        for k in self.DURABLE_WINDOWS:
            st[k] = st[k].at[g, me].set(jnp.asarray(rec[k], st[k].dtype))

    def __init__(self, num_groups: int, population: int, window: int):
        if population < 1 or population > 32:
            raise ValueError("population must be in [1, 32] (uint32 bitmap lanes)")
        self.num_groups = num_groups
        self.population = population
        self.window = window

    # -- geometry shorthands -------------------------------------------------
    @property
    def G(self) -> int:
        return self.num_groups

    @property
    def R(self) -> int:
        return self.population

    @property
    def W(self) -> int:
        return self.window

    @property
    def quorum(self) -> int:
        return self.population // 2 + 1

    # -- quorum-tally plane shorthands ---------------------------------------
    @property
    def collective_tally(self) -> bool:
        """True when this kernel's config selects the collective tally
        (``tally="collective"``): TALLY_LANES ride the delay line as
        per-source ``[G, R]`` broadcast lanes instead of R² pair lanes."""
        return (
            getattr(getattr(self, "config", None), "tally", "pairwise")
            == "collective"
        )

    @property
    def tally_lanes(self) -> FrozenSet[str]:
        return frozenset(self.TALLY_LANES)

    # -- telemetry SPI -------------------------------------------------------
    # The engine attaches a [G, R, K] int32 metric-lane block to the state
    # (core/telemetry.py); each step folds per-tick contributions into it.
    # Presence of the block is a static condition: states without it (the
    # profile_tick ablation, hand-built test states) compile a lane-free
    # variant at zero cost.

    def _telemetry(self, old: Pytree, s: Pytree, c: Any) -> Dict[str, Any]:
        """Hook: lane name -> [G, R] per-tick increments (bool or int32).

        ``old`` is the pre-step state, ``s`` the post-phase state dict,
        ``c`` the step's scratch namespace.  The base implementation
        derives the protocol-generic lanes every kernel has by contract
        (commit_bar) or by common window shape (win_bal); subclasses
        extend the dict with their protocol-specific lanes.
        """
        import jax.numpy as jnp

        tel = {
            "commits": jnp.maximum(s["commit_bar"] - old["commit_bar"], 0),
        }
        n_new = getattr(c, "n_new", None)
        if n_new is not None:
            tel["proposals"] = n_new
        for key in ("next_slot", "prop_bar"):  # common frontier names
            if key in s:
                tel["win_occupancy_hw"] = self._occupancy_span(s, key)
                break
        return tel

    def _occupancy_span(self, s, hi_key: str):
        """Cheap window-occupancy proxy for the high-water lane: the live
        span ``frontier - exec_bar`` clipped to [0, W] — the number of
        slots the ring must keep live, i.e. the window-stall pressure.
        An exact ``count(win_* > 0)`` reduce over [G, R, W] costs ~7% of
        a steady G=4096 CPU tick on its own (ablation-measured), which
        would bust the 5% telemetry budget by itself; the span is O(G,R)
        and is the quantity the propose/append window guards actually
        gate on."""
        import jax.numpy as jnp

        span = s[hi_key] - s["exec_bar"]
        if "vote_bar" in s and hi_key != "vote_bar":
            span = jnp.maximum(span, s["vote_bar"] - s["exec_bar"])
        return jnp.clip(span, 0, self.window)

    def _accumulate_telemetry(self, old: Pytree, s: Pytree, c: Any) -> None:
        """Fold this tick's lane contributions into ``s['telem']`` (no-op
        when the state carries no lane block)."""
        if telemetry.TELEM_KEY in s:
            s[telemetry.TELEM_KEY] = telemetry.accumulate(
                s[telemetry.TELEM_KEY], self._telemetry(old, s, c)
            )

    # -- phase runner --------------------------------------------------------
    def _run_phases(self, s: Pytree, c: Any) -> None:
        """Run the declared :data:`PHASES` in order, each under its
        ``phase_scope``.  Kernels' ``step`` bodies call this after
        building the scratch namespace ``c`` (which must carry
        ``c.old`` — the pre-step state — for the ``telemetry`` phase)."""
        for name, meth in self.PHASES:
            with phase_scope(name):
                getattr(self, meth)(s, c)

    def _phase_build_outbox(self, s: Pytree, c: Any) -> None:
        """Registry wrapper: build the outbox as a named phase.  The
        result lands on ``c.out`` so the phase keeps the uniform
        ``(s, c)`` mutate-in-place signature."""
        c.out = self._build_outbox(s, c)

    def _phase_telemetry(self, s: Pytree, c: Any) -> None:
        """Registry wrapper: the stacked telemetry accumulate as a named
        phase (reads old-vs-new off ``c.old``)."""
        self._accumulate_telemetry(c.old, s, c)

    # -- SPI -----------------------------------------------------------------
    def init_state(self, seed: int = 0) -> Pytree:
        raise NotImplementedError

    def zero_outbox(self) -> Pytree:
        """An all-invalid outbox (flags == 0); defines the outbox structure."""
        raise NotImplementedError

    def step(
        self, state: Pytree, inbox: Pytree, inputs: Dict[str, Any]
    ) -> Tuple[Pytree, Pytree, StepEffects]:
        """Advance one lockstep tick.

        ``inbox`` has the same structure as ``zero_outbox`` but with per-pair
        fields transposed to ``[G, R_dst, R_src]``.  ``inputs`` carries host
        inputs for this tick (client proposals, exec floor, ...).
        """
        raise NotImplementedError

    # JAX static-argument support: kernels are static per (class, geometry,
    # config) so jitted steps cache correctly.  Subclasses store their config
    # dataclass as ``self.config`` so it participates in the cache key.
    def _static_key(self) -> tuple:
        cfg = getattr(self, "config", None)
        cfg_key = dataclasses.astuple(cfg) if dataclasses.is_dataclass(cfg) else cfg
        return (type(self), self.num_groups, self.population, self.window, cfg_key)

    def __hash__(self) -> int:
        return hash(self._static_key())

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, ProtocolKernel)
            and self._static_key() == other._static_key()
        )
