"""The batched lockstep engine: protocol SPI, network model, tick engine,
mesh sharding.

This is the TPU-native replacement for the reference's tokio runtime +
TransportHub mesh (``src/server/transport.rs``): instead of one async event
loop per replica process exchanging TCP frames, thousands of replica groups
live as struct-of-arrays JAX state and exchange fixed-width message records
through a pure-functional network model, stepped in lockstep by one jitted
kernel per tick.
"""

from .protocol import ProtocolKernel, StepEffects
from .netmodel import NetConfig, NetModel, ControlInputs
from .engine import Engine

__all__ = [
    "ProtocolKernel",
    "StepEffects",
    "NetConfig",
    "NetModel",
    "ControlInputs",
    "Engine",
]
