"""Device-mesh sharding for the batched engine.

The scaling design (per the "pick a mesh → annotate shardings → let XLA
insert collectives" recipe): a 2-D logical mesh with axes

- ``group``   — the data-parallel-like axis: independent consensus groups
                are embarrassingly parallel, so ``[G, ...]`` state shards
                here with zero cross-device traffic;
- ``replica`` — the tensor-parallel-like axis: replicas of one group can be
                spread over devices, in which case the netmodel's
                ``swapaxes(1, 2)`` delivery lowers to an all-to-all over
                ICI — the collective analog of the reference's full TCP
                mesh among replicas (``src/server/transport.rs``).
                The quorum-tally plane (``core/quorum.py``,
                ``tally="collective"``) narrows this further: tally
                records ride per-source ``[G, R]`` broadcast lanes whose
                sharded delivery is ONE replica-axis all-gather — the
                NetPaxos-style in-mesh vote tally — instead of the
                pairwise lanes' all-to-all.  Both lane families shard
                under the same ``state_sharding`` rule (leading
                ``[G, R(, ...)]``; ``[D, G, R(, ...)]`` for delay-line
                buffers), so no extra constraint spec is needed: GSPMD
                derives the gather from the lane's receiver-side use.

Multi-host scaling rides the same mesh: groups shard over DCN-connected
hosts (no cross-group traffic crosses DCN), replica all-to-alls stay inside
each host's ICI domain — matching how the reference scales client load
across clusters while keeping consensus chatter inside each group.

Everything runs under plain ``jax.jit`` with ``NamedSharding`` constraints
(GSPMD inserts the collectives); a ``shard_map`` variant is not needed since
no per-device control flow exists.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# the one mesh-spec grammar, defined in the jax-free pre-backend module
# (drivers parse "GxR" before the backend initializes); re-exported here
# because this module is where mesh consumers already look
from ..utils.jaxcompat import parse_mesh  # noqa: F401

Pytree = Any


def make_mesh(
    group_shards: Optional[int] = None,
    replica_shards: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(group, replica)`` mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if group_shards is None:
        group_shards = n // replica_shards
    if group_shards * replica_shards != n:
        raise ValueError(
            f"mesh {group_shards}x{replica_shards} != {n} devices"
        )
    arr = np.array(devices).reshape(group_shards, replica_shards)
    return Mesh(arr, ("group", "replica"))


def mesh_for(
    group_shards: int,
    replica_shards: int,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """:func:`make_mesh` over the FIRST ``group_shards*replica_shards``
    visible devices — the driver-facing form (``--mesh 4x2`` on a v5e-8
    uses all 8 chips; ``--mesh 2x1`` on the same pod uses two), with a
    clear error when the pod is too small."""
    devices = list(devices if devices is not None else jax.devices())
    need = group_shards * replica_shards
    if len(devices) < need:
        raise ValueError(
            f"mesh {group_shards}x{replica_shards} needs {need} devices "
            f"but only {len(devices)} are visible (on CPU, request a "
            "virtual mesh first: utils/jaxcompat.set_cpu_devices(N) / "
            "--xla_force_host_platform_device_count=N)"
        )
    return make_mesh(group_shards, replica_shards, devices[:need])


def check_mesh(mesh: Mesh, G: int, R: int) -> None:
    """Refuse geometry the mesh cannot shard evenly, with an error that
    names the offending axis (the raw XLA/reshape failure is cryptic).

    Uneven sharding would need padding the state arrays — a correctness
    hazard for the int32 consensus lanes (a padded phantom replica would
    vote) — so the engine refuses it outright."""
    gs = mesh.shape["group"]
    rs = mesh.shape["replica"]
    if G % gs != 0:
        raise ValueError(
            f"num_groups G={G} is not divisible by the mesh's "
            f"group_shards={gs}: each device must own an equal slice of "
            "the group axis (pick a mesh whose group axis divides G)"
        )
    if R % rs != 0:
        raise ValueError(
            f"population R={R} is not divisible by the mesh's "
            f"replica_shards={rs}: replica rows cannot be split unevenly "
            "across devices (pick replica_shards dividing R, e.g. "
            f"{'1' if R % 2 else '1 or 2'})"
        )


def state_sharding(mesh: Mesh, state: Pytree) -> Pytree:
    """NamedShardings for a state/outbox pytree.

    Every leaf has leading dims [G, R(, ...)]: shard G over ``group`` and the
    first R axis over ``replica``; trailing dims replicated.
    """

    def spec(leaf) -> NamedSharding:
        if leaf.ndim == 0:
            return NamedSharding(mesh, P())
        axes: list = ["group"]
        if leaf.ndim >= 2:
            axes.append("replica")
        axes += [None] * (leaf.ndim - len(axes))
        return NamedSharding(mesh, P(*axes))

    return jax.tree.map(spec, state)


def netstate_sharding(mesh: Mesh, netstate: Pytree) -> Pytree:
    """NamedShardings for a NetModel netstate.

    ``bufs`` leaves lead with the delay axis ``[D, G, R_src, ...]`` —
    replicate D, shard G/R; ``rng`` is ``[G, R, R]``; scalars replicate.
    """

    def buf_spec(key, leaf):
        if key in ("__pair__", "__bcast__"):
            # lane-packed buffers carry a stacked-lane axis after D:
            # [D, L, G, R_src, ...] — replicate D and L, shard G/R
            axes = [None, None, "group", "replica"] + (
                [None] * (leaf.ndim - 4)
            )
        else:
            axes = [None, "group", "replica"] + [None] * (leaf.ndim - 3)
        return NamedSharding(mesh, P(*axes))

    out = dict(netstate)
    out["bufs"] = {
        k: buf_spec(k, v) for k, v in netstate["bufs"].items()
    }
    out["cursor"] = NamedSharding(mesh, P())
    out["tick"] = NamedSharding(mesh, P())
    out["last_due"] = NamedSharding(mesh, P("group", "replica"))
    out["rng"] = NamedSharding(mesh, P("group", "replica", None))
    return out


def mesh_stamp(group_shards: int, replica_shards: int, G: int) -> dict:
    """The canonical mesh block every artifact stamps (bench.py mesh
    runs, TPUTLAT curves, PROFILE.json mesh-sweep points) — one shared
    schema so trajectory consumers never see divergent spellings."""
    return {
        "mesh": f"{group_shards}x{replica_shards}",
        "group_shards": group_shards,
        "replica_shards": replica_shards,
        "devices": group_shards * replica_shards,
        "groups_per_device": G // group_shards,
    }


def _place_copy(leaf, sharding):
    """``device_put`` that GUARANTEES fresh buffers.

    ``jax.device_put`` short-circuits when the array is already placed
    compatibly — on a 1x1 mesh (or any placement matching the source)
    it returns the SAME buffers, so a later donation of the "copy"
    deletes the caller's original out from under it.  That bit for
    real: the engine's boot template (closed over by the jitted tick
    and reused by ``reset_durable_rows`` and later ``init()`` calls)
    was deleted by the first donated window on a 1x1 mesh, and the
    reset path read freed memory.  An explicit device-side copy first
    makes the promise in the name unconditional; the extra copy is
    init-time only, never on the tick path."""
    import jax.numpy as jnp

    return jax.device_put(jnp.array(leaf, copy=True), sharding)


def shard_pytree(mesh: Mesh, tree: Pytree) -> Pytree:
    """Place a state pytree onto the mesh with the group/replica layout.

    Returns NEW arrays (guaranteed — see :func:`_place_copy`): the
    caller's originals — e.g. the engine's boot template, which the
    jitted tick also closes over — stay valid even when the placed
    copies are later donated."""
    shardings = state_sharding(mesh, tree)
    return jax.tree.map(_place_copy, tree, shardings)


def shard_netstate(mesh: Mesh, netstate: Pytree) -> Pytree:
    """Place a netstate onto the mesh (delay axis replicated; fresh
    buffers guaranteed like :func:`shard_pytree`)."""
    shardings = netstate_sharding(mesh, netstate)
    return jax.tree.map(_place_copy, netstate, shardings)


def constrain_state(mesh: Mesh, state: Pytree) -> Pytree:
    """``with_sharding_constraint`` a state/outbox pytree to its
    group/replica layout — the in-jit form of :func:`shard_pytree`,
    applied at the ``lax.scan`` carry boundary so GSPMD keeps every
    leaf's placement stable across ticks (and lowers the netmodel's
    in-group ``swapaxes`` delivery to the replica-axis all-to-all
    instead of gathering the world to one device)."""
    return jax.lax.with_sharding_constraint(
        state, state_sharding(mesh, state)
    )


def constrain_netstate(mesh: Mesh, netstate: Pytree) -> Pytree:
    """In-jit sharding constraint for a NetModel netstate (see
    :func:`constrain_state`)."""
    return jax.lax.with_sharding_constraint(
        netstate, netstate_sharding(mesh, netstate)
    )
