"""Device-mesh sharding for the batched engine.

The scaling design (per the "pick a mesh → annotate shardings → let XLA
insert collectives" recipe): a 2-D logical mesh with axes

- ``group``   — the data-parallel-like axis: independent consensus groups
                are embarrassingly parallel, so ``[G, ...]`` state shards
                here with zero cross-device traffic;
- ``replica`` — the tensor-parallel-like axis: replicas of one group can be
                spread over devices, in which case the netmodel's
                ``swapaxes(1, 2)`` delivery lowers to an all-to-all over
                ICI — the collective analog of the reference's full TCP
                mesh among replicas (``src/server/transport.rs``).

Multi-host scaling rides the same mesh: groups shard over DCN-connected
hosts (no cross-group traffic crosses DCN), replica all-to-alls stay inside
each host's ICI domain — matching how the reference scales client load
across clusters while keeping consensus chatter inside each group.

Everything runs under plain ``jax.jit`` with ``NamedSharding`` constraints
(GSPMD inserts the collectives); a ``shard_map`` variant is not needed since
no per-device control flow exists.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Pytree = Any


def make_mesh(
    group_shards: Optional[int] = None,
    replica_shards: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``(group, replica)`` mesh over the available devices."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if group_shards is None:
        group_shards = n // replica_shards
    if group_shards * replica_shards != n:
        raise ValueError(
            f"mesh {group_shards}x{replica_shards} != {n} devices"
        )
    arr = np.array(devices).reshape(group_shards, replica_shards)
    return Mesh(arr, ("group", "replica"))


def state_sharding(mesh: Mesh, state: Pytree) -> Pytree:
    """NamedShardings for a state/outbox pytree.

    Every leaf has leading dims [G, R(, ...)]: shard G over ``group`` and the
    first R axis over ``replica``; trailing dims replicated.
    """

    def spec(leaf) -> NamedSharding:
        if leaf.ndim == 0:
            return NamedSharding(mesh, P())
        axes: list = ["group"]
        if leaf.ndim >= 2:
            axes.append("replica")
        axes += [None] * (leaf.ndim - len(axes))
        return NamedSharding(mesh, P(*axes))

    return jax.tree.map(spec, state)


def netstate_sharding(mesh: Mesh, netstate: Pytree) -> Pytree:
    """NamedShardings for a NetModel netstate.

    ``bufs`` leaves lead with the delay axis ``[D, G, R_src, ...]`` —
    replicate D, shard G/R; ``rng`` is ``[G, R, R]``; scalars replicate.
    """

    def buf_spec(key, leaf):
        if key in ("__pair__", "__bcast__"):
            # lane-packed buffers carry a stacked-lane axis after D:
            # [D, L, G, R_src, ...] — replicate D and L, shard G/R
            axes = [None, None, "group", "replica"] + (
                [None] * (leaf.ndim - 4)
            )
        else:
            axes = [None, "group", "replica"] + [None] * (leaf.ndim - 3)
        return NamedSharding(mesh, P(*axes))

    out = dict(netstate)
    out["bufs"] = {
        k: buf_spec(k, v) for k, v in netstate["bufs"].items()
    }
    out["cursor"] = NamedSharding(mesh, P())
    out["tick"] = NamedSharding(mesh, P())
    out["last_due"] = NamedSharding(mesh, P("group", "replica"))
    out["rng"] = NamedSharding(mesh, P("group", "replica", None))
    return out


def shard_pytree(mesh: Mesh, tree: Pytree) -> Pytree:
    """Place a state pytree onto the mesh with the group/replica layout."""
    shardings = state_sharding(mesh, tree)
    return jax.tree.map(jax.device_put, tree, shardings)


def shard_netstate(mesh: Mesh, netstate: Pytree) -> Pytree:
    """Place a netstate onto the mesh (delay axis replicated)."""
    shardings = netstate_sharding(mesh, netstate)
    return jax.tree.map(jax.device_put, netstate, shardings)
