"""Native (C++) components, loaded via ctypes — no pybind11 dependency.

``load_wal()`` returns the ctypes handle to the WAL backend, building the
shared object with the bundled Makefile on first use.  Build failures fall
back to ``None``; callers (``host/storage.py``) must degrade to the pure-
Python mirror so the framework stays usable on toolchain-less machines.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libsummerset_wal.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _configure(lib) -> None:
    lib.wal_open.restype = ctypes.c_void_p
    lib.wal_open.argtypes = [ctypes.c_char_p]
    lib.wal_close.argtypes = [ctypes.c_void_p]
    lib.wal_size.restype = ctypes.c_uint64
    lib.wal_size.argtypes = [ctypes.c_void_p]
    lib.wal_append.restype = ctypes.c_uint64
    lib.wal_append.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
    ]
    lib.wal_write_at.restype = ctypes.c_uint64
    lib.wal_write_at.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
        ctypes.c_int,
    ]
    lib.wal_read.restype = ctypes.c_int64
    lib.wal_read.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
    ]
    lib.wal_truncate.restype = ctypes.c_int
    lib.wal_truncate.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
    ]
    lib.wal_discard.restype = ctypes.c_int
    lib.wal_discard.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int,
    ]


def load_wal():
    """The ctypes library handle, or None when the native build fails."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_SO) or (
                os.path.getmtime(_SO)
                < os.path.getmtime(os.path.join(_DIR, "wal.cpp"))
            ):
                subprocess.run(
                    ["make", "-C", _DIR],
                    check=True,
                    capture_output=True,
                    timeout=120,
                )
            lib = ctypes.CDLL(_SO)
            _configure(lib)
            _lib = lib
        except Exception:
            _lib = None
        return _lib
