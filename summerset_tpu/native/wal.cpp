// Native write-ahead-log backend for the host StorageHub.
//
// Parity target: reference src/server/storage.rs logger task — a flat file
// of 8-byte length-prefixed entries with Read/Write/Append/Truncate/Discard
// actions and optional fsync (storage.rs:192-510).  The reference's logger
// is a tokio task owning the file; here the hot file ops are C++ behind a
// C ABI, driven by a Python worker thread (ctypes, no pybind11 dependency).
//
// Length prefixes are 8-byte little-endian (host order on every supported
// target); bodies are opaque bytes (the Python layer pickles entries).

#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>
#include <vector>

namespace {

struct Wal {
    int fd = -1;
    uint64_t size = 0;  // current end-of-log offset
};

int full_pread(int fd, void* buf, size_t len, uint64_t off) {
    auto* p = static_cast<char*>(buf);
    size_t done = 0;
    while (done < len) {
        ssize_t n = ::pread(fd, p + done, len - done, off + done);
        if (n < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        if (n == 0) return -1;  // unexpected EOF
        done += static_cast<size_t>(n);
    }
    return 0;
}

int full_pwrite(int fd, const void* buf, size_t len, uint64_t off) {
    const auto* p = static_cast<const char*>(buf);
    size_t done = 0;
    while (done < len) {
        ssize_t n = ::pwrite(fd, p + done, len - done, off + done);
        if (n < 0) {
            if (errno == EINTR) continue;
            return -1;
        }
        done += static_cast<size_t>(n);
    }
    return 0;
}

}  // namespace

extern "C" {

// Opens (creating if needed) the log; returns an opaque handle or null.
void* wal_open(const char* path) {
    int fd = ::open(path, O_RDWR | O_CREAT, 0644);
    if (fd < 0) return nullptr;
    struct stat st;
    if (::fstat(fd, &st) != 0) {
        ::close(fd);
        return nullptr;
    }
    auto* w = new Wal();
    w->fd = fd;
    w->size = static_cast<uint64_t>(st.st_size);
    return w;
}

void wal_close(void* h) {
    if (h == nullptr) return;
    auto* w = static_cast<Wal*>(h);
    ::close(w->fd);
    delete w;
}

// Current end-of-log offset.
uint64_t wal_size(void* h) { return static_cast<Wal*>(h)->size; }

// Appends one length-prefixed entry; returns the new end offset, 0 on error.
uint64_t wal_append(void* h, const uint8_t* buf, uint64_t len, int sync) {
    auto* w = static_cast<Wal*>(h);
    uint64_t hdr = len;
    if (full_pwrite(w->fd, &hdr, 8, w->size) != 0) return 0;
    if (full_pwrite(w->fd, buf, len, w->size + 8) != 0) return 0;
    w->size += 8 + len;
    if (sync && ::fdatasync(w->fd) != 0) return 0;
    return w->size;
}

// Writes one entry at `off` (not advancing past existing content beyond
// it); returns the entry's end offset, 0 on error.  Mirrors the
// reference's Write action (storage.rs:282-324): the log is truncated to
// the entry's end if it previously extended further *at this offset
// chain* — here we keep it simple and only extend `size` when writing at
// or past the current end.
uint64_t wal_write_at(void* h, uint64_t off, const uint8_t* buf,
                      uint64_t len, int sync) {
    auto* w = static_cast<Wal*>(h);
    uint64_t hdr = len;
    if (full_pwrite(w->fd, &hdr, 8, off) != 0) return 0;
    if (full_pwrite(w->fd, buf, len, off + 8) != 0) return 0;
    uint64_t end = off + 8 + len;
    if (end > w->size) w->size = end;
    if (sync && ::fdatasync(w->fd) != 0) return 0;
    return end;
}

// Reads the entry at `off` into `out` (capacity `cap`); returns the entry
// length, or -1 on error / truncated tail, or -2 if `cap` is too small
// (call again with a bigger buffer).
int64_t wal_read(void* h, uint64_t off, uint8_t* out, uint64_t cap) {
    auto* w = static_cast<Wal*>(h);
    if (off + 8 > w->size) return -1;
    uint64_t len = 0;
    if (full_pread(w->fd, &len, 8, off) != 0) return -1;
    if (off + 8 + len > w->size) return -1;
    if (len > cap) return -2;
    if (len > 0 && full_pread(w->fd, out, len, off + 8) != 0) return -1;
    return static_cast<int64_t>(len);
}

// Truncates the log to `off` (storage.rs:351-373).  Returns 0 on success.
int wal_truncate(void* h, uint64_t off, int sync) {
    auto* w = static_cast<Wal*>(h);
    if (off > w->size) return -1;
    if (::ftruncate(w->fd, static_cast<off_t>(off)) != 0) return -1;
    w->size = off;
    if (sync && ::fdatasync(w->fd) != 0) return -1;
    return 0;
}

// Discards log content in [keep, off), sliding [off, size) down to `keep`
// (storage.rs:375-413: snapshot GC keeping a `keep`-byte header).
int wal_discard(void* h, uint64_t off, uint64_t keep, int sync) {
    auto* w = static_cast<Wal*>(h);
    if (off < keep || off > w->size) return -1;
    uint64_t tail = w->size - off;
    if (tail > 0) {
        std::vector<uint8_t> buf(1 << 20);
        uint64_t moved = 0;
        while (moved < tail) {
            uint64_t n = tail - moved;
            if (n > buf.size()) n = buf.size();
            if (full_pread(w->fd, buf.data(), n, off + moved) != 0) return -1;
            if (full_pwrite(w->fd, buf.data(), n, keep + moved) != 0)
                return -1;
            moved += n;
        }
    }
    if (::ftruncate(w->fd, static_cast<off_t>(keep + tail)) != 0) return -1;
    w->size = keep + tail;
    if (sync && ::fdatasync(w->fd) != 0) return -1;
    return 0;
}

}  // extern "C"
